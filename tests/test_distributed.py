"""Distributed layer tests: wire round trip, query offload round trip,
multi-server fan-out, edge pub/sub — all as in-process/localhost pipelines
(the reference tests distribution the same way: multiple processes on
localhost, ``tests/nnstreamer_edge/query/runTest.sh``)."""

import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.distributed import WireError, decode_frame, encode_frame
from nnstreamer_tpu.pipeline import parse_pipeline


class TestWire:
    def test_roundtrip(self):
        f = TensorFrame(
            [np.arange(6, dtype=np.float32).reshape(2, 3), np.uint8([1, 2])],
            pts=1.25,
            meta={"client_id": 7, "label": "cat"},
        )
        g = decode_frame(encode_frame(f))
        assert g.pts == 1.25
        assert g.meta["label"] == "cat" and g.meta["client_id"] == 7
        np.testing.assert_array_equal(g.tensors[0], f.tensors[0])
        np.testing.assert_array_equal(g.tensors[1], f.tensors[1])

    def test_no_pts(self):
        g = decode_frame(encode_frame(TensorFrame([np.int32([1])])))
        assert g.pts is None

    def test_non_serializable_meta_skipped(self):
        f = TensorFrame([np.int32([1])], meta={"ok": 1, "bad": object()})
        g = decode_frame(encode_frame(f))
        assert g.meta == {"ok": 1}

    def test_garbage_n(self):
        with pytest.raises(WireError):
            decode_frame(b"not a frame")
        with pytest.raises(WireError):
            decode_frame(b"")


class TestQueryRoundTrip:
    def make_server(self, sid, fw="scaler", custom="factor:2"):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 ! "
            f"tensor_filter framework={fw} custom={custom} ! "
            f"tensor_query_serversink id={sid}"
        )
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_offload_roundtrip(self):
        server, port = self.make_server(101)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} ! tensor_sink name=out"
            )
            client.start()
            for i in range(5):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=20)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [0.0, 2.0, 4.0, 6.0, 8.0]  # scaled by server, in order
        finally:
            server.stop()

    def test_live_stream_emits_without_eos(self):
        """Answers must reach the sink as soon as they land, not when the
        NEXT frame (or EOS) happens to trigger a drain — a sparse live
        stream would otherwise stall with responses parked in the
        in-flight window (regression: burst < max-in-flight)."""
        import time

        server, port = self.make_server(131)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "max-in-flight=8 ! tensor_sink name=out"
            )
            client.start()
            for i in range(2):  # burst smaller than the in-flight window
                client["src"].push(np.float32([i]))
            deadline = time.time() + 10
            while len(client["out"].frames) < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert len(client["out"].frames) == 2, "live drain never fired"
            client["src"].end_of_stream()
            client.wait(timeout=10)
            client.stop()
        finally:
            server.stop()

    def test_wire_batch_ordered_roundtrip(self):
        """wire-batch > 1: already-queued frames ride one RPC; results
        come back per-frame, in order, correctly transformed."""
        server, port = self.make_server(141)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "wire-batch=4 max-in-flight=4 ! tensor_sink name=out"
            )
            client.start()
            n = 11  # odd: forces 1-frame and partial batches too
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=20)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [i * 2.0 for i in range(n)]
        finally:
            server.stop()

    def test_wire_batch_failover_no_loss(self):
        """retries>0 + wire-batch: a server killed mid-stream fails whole
        BATCHES over to the surviving server — at-least-once per frame
        (duplicates legal, loss not)."""
        import time

        s1, p1 = self.make_server(151)
        s2, p2 = self.make_server(152)
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client "
            f"hosts=localhost:{p1},localhost:{p2} wire-batch=4 "
            "max-in-flight=2 retries=2 timeout=5 ! tensor_sink name=out"
        )
        client.start()
        try:
            n = 24
            for i in range(n):
                client["src"].push(np.float32([i]))
                if i == 8:
                    s1.stop()  # kill one server mid-stream
                time.sleep(0.01)
            client["src"].end_of_stream()
            client.wait(timeout=30)
            got = {
                int(float(f.tensors[0][0]) // 2)
                for f in client["out"].frames
            }
            missing = set(range(n)) - got
            assert not missing, f"lost frames: {sorted(missing)}"
        finally:
            client.stop()
            s2.stop()

    def test_wire_batch_envelope_roundtrip(self):
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.distributed.wire import (
            decode_frames,
            encode_frames,
            is_batch_payload,
        )

        frames = [
            TensorFrame([np.full((3,), i, np.int32)], pts=float(i),
                        meta={"i": i})
            for i in range(5)
        ]
        buf = encode_frames(frames)
        assert is_batch_payload(buf)
        back = decode_frames(buf)
        assert len(back) == 5
        for i, f in enumerate(back):
            np.testing.assert_array_equal(
                f.tensors[0], np.full((3,), i, np.int32))
            assert f.pts == float(i) and f.meta["i"] == i
        # a single-frame NNSQ payload is NOT mistaken for an envelope
        from nnstreamer_tpu.distributed.wire import encode_frame

        assert not is_batch_payload(encode_frame(frames[0]))

    def test_fanout_two_servers_ordered(self):
        s1, p1 = self.make_server(111)
        s2, p2 = self.make_server(112)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client hosts=localhost:{p1},localhost:{p2} "
                "max-in-flight=4 ! tensor_sink name=out"
            )
            client.start()
            n = 12
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [2.0 * i for i in range(n)]  # order preserved
        finally:
            s1.stop()
            s2.stop()

    def test_client_unreachable_n(self):
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client port=1 timeout=1.5 ! tensor_sink name=out"
        )
        client.start()
        client["src"].push(np.float32([1]))
        client["src"].end_of_stream()
        with pytest.raises(Exception):
            client.wait(timeout=20)
        client.stop()

    def test_client_id_meta_on_server(self):
        seen = []
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=120 port=0 ! "
            "tensor_filter framework=passthrough ! tensor_query_serversink id=120"
        )
        server.start()
        port = server["ssrc"].props["port"]
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} ! tensor_sink name=out"
            )
            client.start()
            client["src"].push(np.float32([1]))
            client["src"].end_of_stream()
            client.wait(timeout=20)
            assert client["out"].frames[0].meta.get("client_id") is not None
            client.stop()
        finally:
            server.stop()


class TestEdgePubSub:
    def test_publish_subscribe(self):
        sink_pipe = parse_pipeline(
            "appsrc name=src ! edgesink name=es port=0 topic=video"
        )
        sink_pipe.start()
        port = sink_pipe["es"].props["port"]
        try:
            src_pipe = parse_pipeline(
                f"edgesrc dest-port={port} topic=video rebase-pts=false ! tensor_sink name=out"
            )
            src_pipe.start()
            time.sleep(0.5)  # let the subscription attach
            for i in range(3):
                sink_pipe["src"].push(np.int32([i]), pts=i * 0.1)
            deadline = time.time() + 10
            while len(src_pipe["out"].frames) < 3 and time.time() < deadline:
                time.sleep(0.05)
            assert [int(f.tensors[0][0]) for f in src_pipe["out"].frames] == [0, 1, 2]
            src_pipe.stop()
        finally:
            sink_pipe["src"].end_of_stream()
            sink_pipe.wait(timeout=10)
            sink_pipe.stop()

    def test_topic_isolation(self):
        sink_pipe = parse_pipeline(
            "appsrc name=src ! edgesink name=es port=0 topic=a"
        )
        sink_pipe.start()
        port = sink_pipe["es"].props["port"]
        try:
            other = parse_pipeline(
                f"edgesrc dest-port={port} topic=b ! tensor_sink name=out"
            )
            other.start()
            time.sleep(0.3)
            sink_pipe["src"].push(np.int32([1]))
            time.sleep(0.5)
            assert len(other["out"].frames) == 0  # different topic sees nothing
            other.stop()
        finally:
            sink_pipe["src"].end_of_stream()
            sink_pipe.wait(timeout=10)
            sink_pipe.stop()


class TestEdgeTcp:
    """Raw-TCP connect type (≙ reference edge_common.c TCP): a plain
    socket data channel with no gRPC dependency."""

    def test_tcp_publish_subscribe(self):
        tx = parse_pipeline(
            "appsrc name=src ! edgesink name=es connect-type=tcp port=0 "
            "topic=tv"
        )
        tx.start()
        port = tx["es"].props["port"]
        try:
            rx = parse_pipeline(
                f"edgesrc connect-type=tcp dest-port={port} topic=tv "
                "rebase-pts=false ! tensor_sink name=out"
            )
            rx.start()
            deadline = time.time() + 5
            while (tx["es"]._tcp.subscriber_count("tv") < 1
                   and time.time() < deadline):
                time.sleep(0.02)
            for i in range(3):
                tx["src"].push(np.int32([i]), pts=i * 0.1)
            deadline = time.time() + 10
            while len(rx["out"].frames) < 3 and time.time() < deadline:
                time.sleep(0.05)
            vals = [int(f.tensors[0][0]) for f in rx["out"].frames]
            assert vals == [0, 1, 2]
            rx.stop()
        finally:
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()

    def test_sockets_only_external_subscriber(self):
        """A peer with ONLY the socket module + the public framing (u32
        topic prefix in, u32 length-prefixed NNSQ frames out) reads the
        stream — the no-dependency interop contract of the TCP type."""
        import socket
        import struct

        from nnstreamer_tpu.distributed.wire import decode_frame

        tx = parse_pipeline(
            "appsrc name=src ! edgesink name=es connect-type=tcp port=0 "
            "topic=raw"
        )
        tx.start()
        port = tx["es"].props["port"]
        try:
            s = socket.create_connection(("127.0.0.1", port), timeout=5)
            s.sendall(struct.pack("<I", 3) + b"raw")
            deadline = time.time() + 5
            while (tx["es"]._tcp.subscriber_count("raw") < 1
                   and time.time() < deadline):
                time.sleep(0.02)
            tx["src"].push(np.float32([1.5, 2.5]))

            def read_exact(n):
                buf = b""
                while len(buf) < n:
                    chunk = s.recv(n - len(buf))
                    assert chunk, "publisher hung up"
                    buf += chunk
                return buf

            s.settimeout(10)
            (plen,) = struct.unpack("<I", read_exact(4))
            frame = decode_frame(read_exact(plen))
            np.testing.assert_allclose(
                np.asarray(frame.tensors[0]), [1.5, 2.5])
            s.close()
        finally:
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()

    def test_dead_subscriber_dropped_not_fatal(self):
        from nnstreamer_tpu.distributed.tcp_edge import (
            TcpEdgeServer,
            TcpEdgeSubscriber,
        )

        srv = TcpEdgeServer()
        try:
            sub = TcpEdgeSubscriber("127.0.0.1", srv.port, "t")
            deadline = time.time() + 5
            while srv.subscriber_count("t") < 1 and time.time() < deadline:
                time.sleep(0.02)
            assert srv.publish("t", b"x" * 64) == 1
            sub.close()
            time.sleep(0.1)
            # dead peer: delivery count drops to 0, server stays up
            for _ in range(3):
                srv.publish("t", b"y" * 64)
            assert srv.subscriber_count("t") == 0
            # and a new subscriber still works
            sub2 = TcpEdgeSubscriber("127.0.0.1", srv.port, "t")
            deadline = time.time() + 5
            while srv.subscriber_count("t") < 1 and time.time() < deadline:
                time.sleep(0.02)
            assert srv.publish("t", b"z") == 1
            it = sub2.payloads(idle_timeout=5)
            assert next(it) == b"z"
            sub2.close()
        finally:
            srv.close()


class TestEdgeHybrid:
    """MQTT-hybrid connect type: discovery over MQTT, data over gRPC
    (reference CHANGES:8-13 — control/data channel split for throughput)."""

    def test_hybrid_discovery_and_stream(self):
        import numpy as np

        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        mqtt = MiniBroker()
        tx = parse_pipeline(
            f"appsrc name=src ! edgesink topic=hy connect-type=hybrid "
            f"dest-host=127.0.0.1 dest-port={mqtt.port} port=0"
        )
        tx.start()
        rx = parse_pipeline(
            f"edgesrc topic=hy connect-type=hybrid dest-host=127.0.0.1 "
            f"dest-port={mqtt.port} ! tensor_sink name=out"
        )
        rx.start()
        try:
            import time as _t

            _t.sleep(0.3)  # let the subscription attach to the data broker
            for i in range(3):
                tx["src"].push(np.int32([i]), pts=float(i))
            deadline = _t.time() + 10
            while len(rx["out"].frames) < 3 and _t.time() < deadline:
                _t.sleep(0.05)
            vals = [int(np.asarray(f.tensors[0])[0]) for f in rx["out"].frames]
            assert vals == [0, 1, 2]
        finally:
            rx.stop()
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()
            mqtt.close()

    def test_hybrid_discovery_timeout(self):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        mqtt = MiniBroker()  # nobody announces on this broker
        rx = parse_pipeline(
            f"edgesrc topic=ghost connect-type=hybrid dest-host=127.0.0.1 "
            f"dest-port={mqtt.port} discovery-timeout=0.5 ! tensor_sink name=out"
        )
        try:
            with pytest.raises(Exception, match="no edge announce"):
                rx.start()
        finally:
            rx.stop()
            mqtt.close()

    def test_late_subscriber_gets_retained_announce(self):
        """The announce is retained: a source starting AFTER the sink still
        discovers the endpoint."""
        import numpy as np

        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        mqtt = MiniBroker()
        tx = parse_pipeline(
            f"appsrc name=src ! edgesink topic=late connect-type=hybrid "
            f"dest-host=127.0.0.1 dest-port={mqtt.port}"
        )
        tx.start()
        import time as _t

        _t.sleep(0.5)  # announce long since published and retained
        rx = parse_pipeline(
            f"edgesrc topic=late connect-type=hybrid dest-host=127.0.0.1 "
            f"dest-port={mqtt.port} ! tensor_sink name=out"
        )
        rx.start()
        try:
            _t.sleep(0.3)
            tx["src"].push(np.float32([7.0]))
            deadline = _t.time() + 10
            while not rx["out"].frames and _t.time() < deadline:
                _t.sleep(0.05)
            assert rx["out"].frames
        finally:
            rx.stop()
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()
            mqtt.close()

    def test_stopped_sink_clears_retained_announce(self):
        """A stopped hybrid sink deletes its retained announce, so later
        sources time out cleanly instead of dialing the dead port."""
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        mqtt = MiniBroker()
        tx = parse_pipeline(
            f"appsrc name=src ! edgesink topic=gone connect-type=hybrid "
            f"dest-host=127.0.0.1 dest-port={mqtt.port}"
        )
        tx.start()
        import time as _t

        _t.sleep(0.3)
        tx["src"].end_of_stream()
        tx.wait(timeout=10)
        tx.stop()
        _t.sleep(0.3)
        rx = parse_pipeline(
            f"edgesrc topic=gone connect-type=hybrid dest-host=127.0.0.1 "
            f"dest-port={mqtt.port} discovery-timeout=0.6 ! tensor_sink name=out"
        )
        try:
            with pytest.raises(Exception, match="no edge announce"):
                rx.start()
        finally:
            rx.stop()
            mqtt.close()


class TestTcpQueryTransport:
    """connect-type=tcp: the zero-copy raw-TCP data plane
    (distributed/tcp_query.py; ≙ reference nns-edge TCP framing,
    tensor_query_client.c:657-699).  Same QueryServerCore semantics as
    gRPC — caps handshake, client routing, wire micro-batching — over
    sendmsg gather-writes and a per-client socket pool."""

    def make_server(self, sid, fw="scaler", custom="factor:2", caps=""):
        caps_prop = f"caps={caps} " if caps else ""
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            f"connect-type=tcp {caps_prop}! "
            f"tensor_filter framework={fw} custom={custom} ! "
            f"tensor_query_serversink id={sid}"
        )
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_offload_roundtrip_ordered(self):
        server, port = self.make_server(301)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "connect-type=tcp max-in-flight=4 ! tensor_sink name=out"
            )
            client.start()
            for i in range(8):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=20)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [i * 2.0 for i in range(8)]
        finally:
            server.stop()

    def test_wire_batch_roundtrip(self):
        server, port = self.make_server(302)
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "connect-type=tcp wire-batch=4 max-in-flight=4 ! "
                "tensor_sink name=out"
            )
            client.start()
            n = 11
            for i in range(n):
                client["src"].push(np.float32([i]))
            client["src"].end_of_stream()
            client.wait(timeout=20)
            client.stop()
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [i * 2.0 for i in range(n)]
        finally:
            server.stop()

    def test_large_payload_intact(self):
        """150 KB frames survive the gather-send / recv_into path
        bit-exactly (partial sendmsg/recv handling)."""
        server, port = self.make_server(303, fw="scaler", custom="factor:1")
        try:
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "connect-type=tcp wire-batch=2 ! tensor_sink name=out"
            )
            client.start()
            rng = np.random.default_rng(0)
            payloads = [rng.integers(0, 255, (224, 224, 3)).astype(np.float32)
                        for _ in range(4)]
            for p in payloads:
                client["src"].push(p)
            client["src"].end_of_stream()
            client.wait(timeout=30)
            client.stop()
            outs = [np.asarray(f.tensors[0]) for f in client["out"].frames]
            assert len(outs) == 4
            for got, want in zip(outs, payloads):
                np.testing.assert_array_equal(got, want)
        finally:
            server.stop()

    def test_handshake_caps_mismatch_fails(self):
        from nnstreamer_tpu.distributed.tcp_query import TcpQueryConnection

        server, port = self.make_server(
            304, caps="other/tensors,num_tensors=1,dimensions=2,types=float32")
        try:
            conn = TcpQueryConnection("127.0.0.1", port, timeout=5)
            try:
                with pytest.raises(RuntimeError, match="caps mismatch"):
                    conn.handshake(
                        "other/tensors,num_tensors=1,dimensions=7,types=uint8")
                # matching caps pass
                got = conn.handshake(
                    "other/tensors,num_tensors=1,dimensions=2,types=float32")
                assert "float32" in got
            finally:
                conn.close()
        finally:
            server.stop()

    def test_dead_server_raises_promptly(self):
        from nnstreamer_tpu.distributed.tcp_query import TcpQueryConnection
        from nnstreamer_tpu.core.buffer import TensorFrame

        conn = TcpQueryConnection("127.0.0.1", 1, timeout=2)  # nothing there
        try:
            with pytest.raises((ConnectionError, OSError)):
                conn.invoke(TensorFrame((np.float32([1]),)))
        finally:
            conn.close()

    def test_socket_pool_parallel_invokes(self):
        """N threads invoking concurrently each get their own socket;
        results match their requests (no cross-talk)."""
        import threading

        from nnstreamer_tpu.distributed.tcp_query import TcpQueryConnection
        from nnstreamer_tpu.core.buffer import TensorFrame

        server, port = self.make_server(305)
        try:
            conn = TcpQueryConnection("127.0.0.1", port, timeout=10, nconns=4)
            errs, results = [], {}

            def worker(i):
                try:
                    out = conn.invoke(TensorFrame((np.float32([i]),)))
                    results[i] = float(out.tensors[0][0])
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=20)
            conn.close()
            assert not errs
            assert results == {i: i * 2.0 for i in range(8)}
        finally:
            server.stop()

    def test_tcp_failover_no_loss(self):
        """Same elastic contract as the gRPC leg: a TCP server killed
        mid-stream fails whole batches over to the survivor (retries>0,
        at-least-once)."""
        import time

        s1, p1 = self.make_server(306)
        s2, p2 = self.make_server(307)
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client connect-type=tcp "
            f"hosts=localhost:{p1},localhost:{p2} wire-batch=4 "
            "max-in-flight=2 retries=2 timeout=5 ! tensor_sink name=out"
        )
        client.start()
        try:
            n = 24
            for i in range(n):
                client["src"].push(np.float32([i]))
                if i == 8:
                    s1.stop()  # kill one server mid-stream
                time.sleep(0.01)
            client["src"].end_of_stream()
            client.wait(timeout=30)
            got = {
                int(float(f.tensors[0][0]) // 2)
                for f in client["out"].frames
            }
            missing = set(range(n)) - got
            assert not missing, f"lost frames: {sorted(missing)}"
        finally:
            client.stop()
            s2.stop()
