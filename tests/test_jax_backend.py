"""jax-xla backend tests (CPU-forced via conftest; TPU path in bench.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.backends import find_backend
from nnstreamer_tpu.backends.jax_xla import register_jax_model, unregister_jax_model
from nnstreamer_tpu.core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def affine_model():
    # y = 2x + 1 — trivially verifiable through the jit path
    params = {"w": jnp.float32(2.0), "b": jnp.float32(1.0)}
    register_jax_model("affine", lambda p, xs: [xs[0] * p["w"] + p["b"]], params)
    yield
    unregister_jax_model("affine")


class TestJaxXlaBackend:
    def test_invoke(self, affine_model):
        be = find_backend("jax-xla")()
        be.open("affine", {})
        out = be.invoke([np.float32([1, 2, 3])])
        np.testing.assert_allclose(np.asarray(out[0]), [3, 5, 7])
        be.close()

    def test_invoke_batch_bucketing(self, affine_model):
        be = find_backend("jax-xla")()
        be.open("affine", {})
        # batch of 5 pads to bucket 8, slices back to 5
        out = be.invoke_batch([np.ones((5, 4), np.float32)])
        assert np.asarray(out[0]).shape == (5, 4)
        np.testing.assert_allclose(np.asarray(out[0]), 3.0)
        # same bucket reuses the compiled executable
        assert len(be._jit_cache) == 1
        out = be.invoke_batch([np.ones((7, 4), np.float32)])
        assert np.asarray(out[0]).shape == (7, 4)
        assert len(be._jit_cache) == 1  # still bucket 8
        be.close()

    def test_set_input_info_eval_shape(self, affine_model):
        be = find_backend("jax-xla")()
        be.open("affine", {})
        out_spec = be.set_input_info(
            StreamSpec((TensorSpec((4,), np.float32),), FORMAT_STATIC)
        )
        assert out_spec.tensors[0].shape == (4,)
        assert out_spec.tensors[0].dtype == np.dtype(np.float32)
        be.close()

    def test_outputs_stay_on_device(self, affine_model):
        be = find_backend("jax-xla")()
        be.open("affine", {})
        out = be.invoke([np.float32([1.0])])
        assert isinstance(out[0], jax.Array)  # no host round trip
        be.close()

    def test_unresolvable_model_n(self):
        be = find_backend("jax-xla")()
        with pytest.raises(FileNotFoundError):
            be.open("no_such_model", {})

    def test_py_file_model(self, tmp_path, affine_model):
        p = tmp_path / "model.py"
        p.write_text(
            "import jax.numpy as jnp\n"
            "def get_model():\n"
            "    return (lambda params, xs: [xs[0] ** 2], None)\n"
        )
        be = find_backend("jax-xla")()
        be.open(str(p), {})
        out = be.invoke([np.float32([3.0])])
        np.testing.assert_allclose(np.asarray(out[0]), [9.0])
        be.close()

    def test_donated_entry_skips_donation_on_cpu(self, affine_model):
        """invoke_batch_donated on CPU: XLA ignores donation (and warns
        per compile), so the donated entry point must not request it —
        donated_calls counts the routing, donated_applied stays 0, and
        results are identical to the plain path."""
        be = find_backend("jax-xla")()
        be.open("affine", {})
        x = np.ones((4, 3), np.float32)
        out = be.timed_invoke_batch_donated([x.copy()])
        np.testing.assert_allclose(np.asarray(out[0]), 3.0)
        assert be.stats.donated_calls == 1
        assert be.stats.donated_applied == 0  # CPU: donation skipped
        # same executable as the plain path (no donated compile forked)
        be.invoke_batch([x.copy()])
        assert len(be._jit_cache) == 1
        be.close()

    def test_donate_custom_prop_forces_donation(self, affine_model):
        """custom=donate:true pins donation even on CPU (the legacy
        opt-in: the caller takes responsibility for input privacy) —
        the compiled call carries donate_argnums and results stay
        correct (XLA on CPU ignores the alias request, warning only)."""
        be = find_backend("jax-xla")()
        be.open("affine", {"custom": "donate:true"})
        x = np.arange(12, dtype=np.float32).reshape(4, 3)
        out = be.timed_invoke_batch_donated([x.copy()])
        np.testing.assert_allclose(np.asarray(out[0]), x * 2.0 + 1.0)
        assert be.stats.donated_applied == 1
        # the donated variant compiled under its own cache key
        assert any(key[0] is True for key in be._jit_cache)
        be.close()

    def test_to_device_never_aliases_staging_buffer(self, affine_model):
        """The staging lane's buffer-reuse contract: to_device must have
        fully copied OFF the host array before returning.  XLA's CPU
        client zero-copies aligned numpy buffers in device_put, so a
        naive placement would hand back a jax.Array aliasing the pooled
        staging buffer — mutating the buffer afterwards (exactly what
        the lane does for the next batch) must not change the staged
        values."""
        be = find_backend("jax-xla")()
        be.open("affine", {})
        buf = np.ones((4, 3), np.float32)
        dev = be.to_device([buf])
        buf[:] = 777.0  # the lane reuses the staging buffer immediately
        np.testing.assert_allclose(np.asarray(dev[0]), 1.0)
        be.close()

    def test_hot_reload_swaps_params(self, affine_model):
        params2 = {"w": jnp.float32(10.0), "b": jnp.float32(0.0)}
        register_jax_model("affine2", lambda p, xs: [xs[0] * p["w"] + p["b"]], params2)
        try:
            be = find_backend("jax-xla")()
            be.open("affine", {})
            np.testing.assert_allclose(np.asarray(be.invoke([np.float32([1])])[0]), [3])
            be.reload("affine2")
            np.testing.assert_allclose(np.asarray(be.invoke([np.float32([1])])[0]), [10])
            be.close()
        finally:
            unregister_jax_model("affine2")


class TestJaxXlaInPipeline:
    def test_pipeline_with_batching(self, affine_model):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=jax-xla model=affine "
            "max-batch=8 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(12):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        pipe.stop()
        vals = [float(f.tensors[0][0]) for f in pipe["out"].frames]
        assert vals == [2.0 * i + 1.0 for i in range(12)]


class TestMobileNetV2:
    @pytest.mark.slow  # tier-1 budget: ~20s mobilenet compile; the
    # kws/mnist family forwards keep the zoo-backend path covered
    def test_forward_shapes_cpu(self):
        # tiny input keeps CPU compile fast; real 224 path runs in bench.py
        from nnstreamer_tpu.models import build

        fn, params, in_spec, out_spec = build(
            "mobilenet_v2", {"size": "32", "classes": "10", "dtype": "float32"}
        )
        img = np.random.default_rng(0).integers(0, 255, (32, 32, 3), np.uint8)
        out = fn(params, [jnp.asarray(img)])
        assert np.asarray(out[0]).shape == (10,)
        batch = jnp.stack([jnp.asarray(img)] * 2)
        out_b = fn(params, [batch])
        assert np.asarray(out_b[0]).shape == (2, 10)
        # deterministic given fixed seed/params
        np.testing.assert_allclose(
            np.asarray(out_b[0][0]), np.asarray(out[0]), rtol=1e-5, atol=1e-5
        )

    def test_zoo_unknown_n(self):
        from nnstreamer_tpu.models import build

        with pytest.raises(KeyError):
            build("resnet9000")
