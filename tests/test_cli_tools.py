"""L6 tools: pbtxt converter, confchk, codegen, launch CLI."""

import os
import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu.cli import codegen, confchk, pbtxt
from nnstreamer_tpu.pipeline import parse_pipeline


class TestPbtxt:
    def test_linear_roundtrip(self):
        text = (
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=add:1 ! tensor_sink name=out"
        )
        pb = pbtxt.pipeline_text_to_pbtxt(text)
        assert 'type: "tensor_transform"' in pb
        assert 'key: "option"' in pb and 'value: "add:1"' in pb
        assert 'link { src: "src" src_pad: 0 sink:' in pb
        text2 = pbtxt.pbtxt_to_pipeline_text(pb)
        # the regenerated text must itself produce an equivalent pbtxt
        assert pbtxt.pipeline_text_to_pbtxt(text2) == pb

    def test_branching_roundtrip(self):
        text = (
            "appsrc name=a ! mux.  appsrc name=b ! mux.  "
            "tensor_mux name=mux sync-mode=nosync ! tensor_sink name=out"
        )
        pb = pbtxt.pipeline_text_to_pbtxt(text)
        assert pb.count("node {") == 4
        assert pb.count("link {") == 3
        pipe = pbtxt.pbtxt_to_pipeline(pb)
        # run it: 2-pad mux still works after the roundtrip
        pipe.start()
        pipe["a"].push(np.int32([1]))
        pipe["b"].push(np.int32([2]))
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(pipe["out"].frames[0].tensors) == 2

    def test_tee_fanout_roundtrip(self):
        text = (
            "appsrc name=src ! tee name=t  "
            "t. ! tensor_sink name=s1  t. ! tensor_sink name=s2"
        )
        pb = pbtxt.pipeline_text_to_pbtxt(text)
        text2 = pbtxt.pbtxt_to_pipeline_text(pb)
        # regenerated text must parse and produce the identical pbtxt
        assert pbtxt.pipeline_text_to_pbtxt(text2) == pb

    def test_mux_sink_pad_order_preserved(self):
        # pbtxt links listed in REVERSE pad order: regenerated text must
        # still put a on pad 1 and b on pad 0
        pb = (
            'node { name: "a" type: "appsrc" }\n'
            'node { name: "b" type: "appsrc" }\n'
            'node { name: "m" type: "tensor_mux" }\n'
            'node { name: "out" type: "tensor_sink" }\n'
            'link { src: "a" src_pad: 0 sink: "m" sink_pad: 1 }\n'
            'link { src: "b" src_pad: 0 sink: "m" sink_pad: 0 }\n'
            'link { src: "m" src_pad: 0 sink: "out" sink_pad: 0 }\n'
        )
        text = pbtxt.pbtxt_to_pipeline_text(pb)
        pipe = parse_pipeline(text)
        pipe.start()
        pipe["a"].push(np.int32([1]))
        pipe["b"].push(np.int32([2]))
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        # pad 0 (b) first, pad 1 (a) second
        assert [int(t[0]) for t in f.tensors] == [2, 1]

    def test_quote_escaping_roundtrip(self):
        text = 'appsrc name=src ! tensor_sink name=out'
        pipe = parse_pipeline(text)
        # poke a property value containing quotes/backslash through pbtxt
        pb = pbtxt.pipeline_to_pbtxt(pipe).replace(
            'name: "src"', 'name: "src"'
        )
        pipe2 = pbtxt.pbtxt_to_pipeline(pb)
        assert set(pipe2.elements) == {"src", "out"}
        # writer escapes embedded quotes so its own output re-parses
        from nnstreamer_tpu.cli.pbtxt import _q

        assert _q('a="b"') == 'a=\\"b\\"'

    def test_bad_pbtxt(self):
        from nnstreamer_tpu.pipeline.parser import ParseError

        with pytest.raises(ParseError):
            pbtxt.pbtxt_to_pipeline("node { name: unbalanced")
        with pytest.raises(ParseError):
            pbtxt.pbtxt_to_pipeline('node { name: "x" type: "nonexistent" }')


class TestConfchk:
    def test_report_lists_elements_and_backends(self):
        rep = confchk.report()
        assert "tensor_filter" in rep
        assert "tensor_converter" in rep
        assert "filter subplugins" in rep
        assert "jax-xla" in rep
        assert "decoder subplugins" in rep


class TestCodegen:
    def test_python_scaffold_is_loadable(self, tmp_path):
        (path,) = codegen.generate("my_scaler", "python", str(tmp_path))
        ns = {}
        exec(compile(open(path).read(), path, "exec"), ns)
        flt = ns["filter"]("")
        out = flt.invoke([np.zeros((3, 4, 4), np.uint8)])
        assert out[0].shape == (3, 4, 4)

    def test_c_scaffold_compiles_and_runs(self, tmp_path):
        files = codegen.generate("my_native", "c", str(tmp_path))
        assert any(f.endswith(".c") for f in files)
        r = subprocess.run(
            ["make", "-C", str(tmp_path)], capture_output=True, text=True
        )
        assert r.returncode == 0, r.stderr
        so = tmp_path / "my_native.so"
        assert so.exists()
        # run through the custom-native backend
        from nnstreamer_tpu.backends.custom_native import CustomNative

        be = CustomNative()
        be.open(str(so), {})
        ins, outs = be.get_model_info()
        assert tuple(ins.tensors[0].shape) == (3, 224, 224)
        x = np.arange(3 * 224 * 224, dtype=np.uint8).reshape(3, 224, 224)
        (y,) = be.invoke([x])
        np.testing.assert_array_equal(x, y)
        be.close()


class TestLaunchCli:
    def test_launch_runs_pipeline(self):
        r = subprocess.run(
            [
                sys.executable,
                "-m",
                "nnstreamer_tpu.cli.launch",
                "-q",
                "videotestsrc num-buffers=2 ! tensor_converter ! "
                "tensor_sink name=out",
            ],
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            timeout=120,
        )
        assert r.returncode == 0, r.stderr


class TestInspectCli:
    def test_list_all_elements(self, capsys):
        from nnstreamer_tpu.cli.inspect import main

        assert main([]) == 0
        out = capsys.readouterr().out
        assert "tensor_filter" in out and "appsrc" in out
        assert "decoder subplugins" in out

    def test_inspect_element_properties(self, capsys):
        from nnstreamer_tpu.cli.inspect import main

        assert main(["tensor_filter"]) == 0
        out = capsys.readouterr().out
        assert "framework" in out and "max-batch" in out

    def test_unknown_element_suggests(self, capsys):
        from nnstreamer_tpu.cli.inspect import main

        assert main(["tensor_filt"]) == 1
        out = capsys.readouterr().out
        assert "did you mean" in out and "tensor_filter" in out


class TestConvertCli:
    """nns-tpu-convert: third-party model -> native .jaxexport artifact
    (≙ vendor offline compilers: snpe-onnx-to-dlc, edgetpu_compiler)."""

    def test_tflite_roundtrip(self, tmp_path):
        from test_tflite_import import build_affine_tflite
        from nnstreamer_tpu.cli.convert import main as convert_main
        from nnstreamer_tpu.elements.filter import SingleShot

        src = tmp_path / "aff.tflite"
        src.write_bytes(build_affine_tflite())
        dst = tmp_path / "aff.jaxexport"
        assert convert_main([str(src), str(dst)]) == 0
        with SingleShot("jax-xla", str(dst)) as m:
            (out,) = m.invoke([np.full((1, 4), 3.0, np.float32)])
            np.testing.assert_allclose(np.asarray(out),
                                       np.full((1, 4), 7.0))

    def test_onnx_default_output_name(self, tmp_path):
        from test_onnx_import import build_mlp
        from nnstreamer_tpu.cli.convert import main as convert_main

        blob, _ = build_mlp()
        src = tmp_path / "mlp.onnx"
        src.write_bytes(blob)
        assert convert_main([str(src)]) == 0
        assert (tmp_path / "mlp.jaxexport").exists()

    def test_unsupported_format_fails_clearly(self, tmp_path):
        from nnstreamer_tpu.cli.convert import main as convert_main

        src = tmp_path / "model.caffemodel"
        src.write_bytes(b"x")
        with pytest.raises(SystemExit, match="unsupported source format"):
            convert_main([str(src)])

    def test_convert_conv_model_batch_polymorphic(self, tmp_path):
        """Shape-sensitive graphs (Conv) convert with the default
        symbolic batch dim and serve micro-batched (regression: the
        extra axis must vmap, never reach the conv)."""
        from test_onnx_import import build_cnn
        from nnstreamer_tpu.cli.convert import main as convert_main
        from nnstreamer_tpu.backends.jax_xla import JaxXla

        blob, _ = build_cnn()
        src = tmp_path / "cnn.onnx"
        src.write_bytes(blob)
        dst = tmp_path / "cnn.jaxexport"
        assert convert_main([str(src), str(dst)]) == 0
        be = JaxXla()
        be.open(str(dst), {})
        try:
            xs = np.random.default_rng(0).standard_normal(
                (3, 1, 3, 16, 16)).astype(np.float32)
            (out,) = be.invoke_batch([xs])
            assert np.asarray(out).shape == (3, 1, 5)
            (o1,) = be.invoke([xs[0]])
            np.testing.assert_allclose(np.asarray(out)[0],
                                       np.asarray(o1), rtol=1e-5)
        finally:
            be.close()
