"""End-to-end slice: source -> converter -> filter -> decoder -> sink.

The minimum viable pipeline from SURVEY §7 stage 4, using a deterministic
custom-easy "classifier" instead of a real model (the reference tests element
behavior with fake backends the same way).
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.backends import register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def labels_file(tmp_path):
    p = tmp_path / "labels.txt"
    p.write_text("cat\ndog\nbird\n")
    return str(p)


@pytest.fixture
def brightness_classifier():
    """3-class 'model': classify mean brightness of an image batch."""

    def fn(xs):
        img = np.asarray(xs[0], np.float32)
        mean = img.mean()
        scores = np.stack(
            [
                np.exp(-abs(mean - 64.0) / 32),
                np.exp(-abs(mean - 128.0) / 32),
                np.exp(-abs(mean - 192.0) / 32),
            ]
        ).astype(np.float32)
        return [scores]

    register_custom_easy(
        "brightness",
        fn,
        out_spec=StreamSpec((TensorSpec((3,), np.float32, "scores"),), FORMAT_STATIC),
    )
    yield
    unregister_custom_easy("brightness")


class TestEndToEnd:
    def test_video_label_pipeline(self, labels_file, brightness_classifier):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=6 width=32 height=32 pattern=solid ! "
            "tensor_converter ! "
            "tensor_filter framework=custom-easy model=brightness ! "
            f"tensor_decoder mode=image_labeling option1={labels_file} ! "
            "tensor_sink name=out"
        )
        pipe.run(timeout=20)
        frames = pipe["out"].frames
        assert len(frames) == 6
        for f in frames:
            assert "label" in f.meta
            assert f.meta["label"] in ("cat", "dog", "bird")
        # solid pattern brightens per frame index (i*8): first frames darkest
        assert frames[0].meta["label"] == "cat"

    def test_converter_frames_per_tensor(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=6 width=8 height=8 ! "
            "tensor_converter frames-per-tensor=3 ! tensor_sink name=out"
        )
        pipe.run(timeout=20)
        frames = pipe["out"].frames
        assert len(frames) == 2
        assert frames[0].tensors[0].shape == (3, 8, 8, 3)

    def test_converter_octet_mode(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_converter input-dim=4:2 input-type=uint16 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        raw = np.arange(16, dtype=np.uint8)  # 16 bytes -> (2,4) uint16
        pipe["src"].push(raw)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()
        out = pipe["out"].frames[0].tensors[0]
        assert out.dtype == np.uint16 and out.shape == (2, 4)
        np.testing.assert_array_equal(out, raw.view(np.uint16).reshape(2, 4))

    def test_direct_video_decoder(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=2 width=16 height=16 ! tensor_converter ! "
            "tensor_filter framework=passthrough ! "
            "tensor_decoder mode=direct_video ! tensor_sink name=out"
        )
        pipe.run(timeout=20)
        f = pipe["out"].frames[0]
        assert f.meta.get("media") == "video"
        assert f.tensors[0].shape == (16, 16, 3) and f.tensors[0].dtype == np.uint8

    def test_decoder_unknown_mode_n(self):
        pipe = parse_pipeline(
            "videotestsrc num-buffers=1 ! tensor_decoder mode=nope ! tensor_sink"
        )
        with pytest.raises(Exception, match="unknown decoder mode"):
            pipe.start()
        pipe.stop()
