"""ONNX importer: parse .onnx protobufs and lower to XLA.

No ``onnx`` package (and no torch.onnx export) exists in this image, so
test models are hand-encoded with a minimal protobuf writer below — an
independent encoder against the public onnx.proto3 schema — and op
semantics are cross-checked against torch (an independent conv/pool
implementation).  ≙ reference onnx-capable subplugin tests
(``tests/nnstreamer_filter_*``), but the runtime here is XLA.
"""

import struct

import numpy as np
import pytest

from nnstreamer_tpu.importers.onnx_reader import (
    OnnxParseError, read_onnx)
from nnstreamer_tpu.importers.onnx_lower import (
    OnnxLowerError, _Lowering, lower_onnx)


# -- minimal protobuf writer (public onnx.proto3 field numbers) --------------

def _varint(v: int) -> bytes:
    if v < 0:
        v += 1 << 64
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _field(fno: int, wt: int, payload: bytes) -> bytes:
    return _varint((fno << 3) | wt) + payload


def _ld(fno: int, data: bytes) -> bytes:
    return _field(fno, 2, _varint(len(data)) + data)


def _vint(fno: int, v: int) -> bytes:
    return _field(fno, 0, _varint(v))


_DTYPE_CODES = {"float32": 1, "uint8": 2, "int8": 3, "int32": 6,
                "int64": 7, "bool": 9, "float64": 11}


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    arr = np.asarray(arr)
    out = b"".join(_vint(1, int(d)) for d in arr.shape)
    out += _vint(2, _DTYPE_CODES[str(arr.dtype)])
    out += _ld(8, name.encode())
    out += _ld(9, arr.tobytes())
    return out


def attr_proto(name: str, value) -> bytes:
    out = _ld(1, name.encode())
    if isinstance(value, float):
        out += _field(2, 5, struct.pack("<f", value)) + _vint(20, 1)
    elif isinstance(value, bool) or isinstance(value, int):
        out += _vint(3, int(value)) + _vint(20, 2)
    elif isinstance(value, bytes):
        out += _ld(4, value) + _vint(20, 3)
    elif isinstance(value, np.ndarray):
        out += _ld(5, tensor_proto("", value)) + _vint(20, 4)
    elif isinstance(value, (list, tuple)) and value and isinstance(
            value[0], float):
        out += _ld(7, b"".join(struct.pack("<f", v) for v in value))
        out += _vint(20, 6)
    elif isinstance(value, (list, tuple)):
        out += _ld(8, b"".join(_varint(int(v)) for v in value))
        out += _vint(20, 7)
    else:
        raise TypeError(type(value))
    return out


def node_proto(op: str, inputs, outputs, **attrs) -> bytes:
    out = b"".join(_ld(1, i.encode()) for i in inputs)
    out += b"".join(_ld(2, o.encode()) for o in outputs)
    out += _ld(4, op.encode())
    out += b"".join(_ld(5, attr_proto(k, v)) for k, v in attrs.items())
    return out


def value_info(name: str, shape, dtype="float32") -> bytes:
    dims = b"".join(_ld(1, _vint(1, int(d))) for d in shape)
    tensor_type = _vint(1, _DTYPE_CODES[dtype]) + _ld(2, dims)
    return _ld(1, name.encode()) + _ld(2, _ld(1, tensor_type))


def model_proto(nodes, initializers, inputs, outputs, opset=13) -> bytes:
    graph = b"".join(_ld(1, n) for n in nodes)
    graph += b"".join(_ld(5, t) for t in initializers)
    graph += b"".join(_ld(11, v) for v in inputs)
    graph += b"".join(_ld(12, v) for v in outputs)
    model = _vint(1, 8)                       # ir_version
    model += _ld(8, _vint(2, opset))          # opset_import
    model += _ld(7, graph)
    return model


# -- fixture models ----------------------------------------------------------

def build_mlp() -> bytes:
    """x(1,8) -> Gemm(w1,b1) -> Relu -> Gemm(w2,b2) -> Softmax."""
    rng = np.random.default_rng(0)
    w1 = rng.standard_normal((8, 16), np.float32)
    b1 = rng.standard_normal(16).astype(np.float32)
    w2 = rng.standard_normal((16, 4), np.float32)
    b2 = rng.standard_normal(4).astype(np.float32)
    nodes = [
        node_proto("Gemm", ["x", "w1", "b1"], ["h"]),
        node_proto("Relu", ["h"], ["hr"]),
        node_proto("Gemm", ["hr", "w2", "b2"], ["logits"]),
        node_proto("Softmax", ["logits"], ["y"], axis=-1),
    ]
    inits = [tensor_proto("w1", w1), tensor_proto("b1", b1),
             tensor_proto("w2", w2), tensor_proto("b2", b2)]
    blob = model_proto(
        nodes, inits,
        [value_info("x", (1, 8))], [value_info("y", (1, 4))])
    return blob, (w1, b1, w2, b2)


def build_cnn() -> bytes:
    """x(1,3,16,16) -> Conv(s2,p1) -> BatchNorm -> Relu -> MaxPool(2) ->
    GlobalAveragePool -> Flatten -> Gemm."""
    rng = np.random.default_rng(1)
    w = rng.standard_normal((8, 3, 3, 3), np.float32) * 0.2
    b = rng.standard_normal(8).astype(np.float32)
    gamma = rng.random(8).astype(np.float32) + 0.5
    beta = rng.standard_normal(8).astype(np.float32)
    mean = rng.standard_normal(8).astype(np.float32)
    var = rng.random(8).astype(np.float32) + 0.5
    fc_w = rng.standard_normal((8, 5), np.float32)
    fc_b = rng.standard_normal(5).astype(np.float32)
    nodes = [
        node_proto("Conv", ["x", "w", "b"], ["c"],
                   kernel_shape=[3, 3], strides=[2, 2], pads=[1, 1, 1, 1]),
        node_proto("BatchNormalization",
                   ["c", "gamma", "beta", "mean", "var"], ["bn"],
                   epsilon=1e-5),
        node_proto("Relu", ["bn"], ["r"]),
        node_proto("MaxPool", ["r"], ["p"],
                   kernel_shape=[2, 2], strides=[2, 2]),
        node_proto("GlobalAveragePool", ["p"], ["g"]),
        node_proto("Flatten", ["g"], ["f"], axis=1),
        node_proto("Gemm", ["f", "fc_w", "fc_b"], ["y"]),
    ]
    inits = [tensor_proto(n, a) for n, a in [
        ("w", w), ("b", b), ("gamma", gamma), ("beta", beta),
        ("mean", mean), ("var", var), ("fc_w", fc_w), ("fc_b", fc_b)]]
    blob = model_proto(
        nodes, inits,
        [value_info("x", (1, 3, 16, 16))], [value_info("y", (1, 5))])
    return blob, (w, b, gamma, beta, mean, var, fc_w, fc_b)


def build_shape_chain() -> bytes:
    """The torch-export flatten idiom: Shape -> Gather -> Unsqueeze ->
    Concat with [-1] -> Reshape."""
    nodes = [
        node_proto("Shape", ["x"], ["s"]),
        node_proto("Gather", ["s", "i0"], ["n"], axis=0),
        node_proto("Unsqueeze", ["n", "ax0"], ["nu"]),
        node_proto("Concat", ["nu", "minus1"], ["tgt"], axis=0),
        node_proto("Reshape", ["x", "tgt"], ["y"]),
    ]
    inits = [
        tensor_proto("i0", np.asarray(0, np.int64)),
        tensor_proto("ax0", np.asarray([0], np.int64)),
        tensor_proto("minus1", np.asarray([-1], np.int64)),
    ]
    return model_proto(
        nodes, inits,
        [value_info("x", (2, 3, 4))], [value_info("y", (2, 12))])


# -- parser ------------------------------------------------------------------

class TestOnnxReader:
    def test_rejects_garbage(self):
        with pytest.raises(OnnxParseError):
            read_onnx(b"\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff\xff")
        with pytest.raises(OnnxParseError):
            read_onnx(b"TFL3 is not onnx....")

    def test_mlp_structure(self):
        blob, _ = build_mlp()
        m = read_onnx(blob)
        assert m.opset == 13
        assert [vi.name for vi in m.inputs] == ["x"]  # inits excluded
        assert m.inputs[0].shape == (1, 8)
        assert m.op_histogram() == {
            "Gemm": 2, "Relu": 1, "Softmax": 1}
        assert m.initializers["w1"].shape == (8, 16)

    def test_negative_int_attr(self):
        blob, _ = build_mlp()
        m = read_onnx(blob)
        soft = [n for n in m.nodes if n.op_type == "Softmax"][0]
        assert soft.attrs["axis"] == -1  # two's-complement varint decode


# -- lowering ----------------------------------------------------------------

class TestOnnxLowering:
    def test_mlp_matches_numpy(self):
        blob, (w1, b1, w2, b2) = build_mlp()
        fn = lower_onnx(read_onnx(blob))
        x = np.random.default_rng(2).standard_normal((1, 8)).astype(
            np.float32)
        (y,) = fn(x)
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2 + b2
        e = np.exp(logits - logits.max())
        want = e / e.sum()
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-5)

    def test_cnn_matches_torch(self):
        import torch
        import torch.nn.functional as F

        blob, (w, b, gamma, beta, mean, var, fc_w, fc_b) = build_cnn()
        fn = lower_onnx(read_onnx(blob))
        x = np.random.default_rng(3).standard_normal(
            (1, 3, 16, 16)).astype(np.float32)
        (y,) = fn(x)

        xt = torch.from_numpy(x)
        c = F.conv2d(xt, torch.from_numpy(w), torch.from_numpy(b),
                     stride=2, padding=1)
        bn = F.batch_norm(c, torch.from_numpy(mean), torch.from_numpy(var),
                          torch.from_numpy(gamma), torch.from_numpy(beta),
                          eps=1e-5)
        p = F.max_pool2d(F.relu(bn), 2, 2)
        g = p.mean(dim=(2, 3))
        want = (g @ torch.from_numpy(fc_w) + torch.from_numpy(fc_b)).numpy()
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4,
                                   atol=1e-4)

    def test_shape_chain_folds(self):
        fn = lower_onnx(read_onnx(build_shape_chain()))
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        (y,) = fn(x)
        np.testing.assert_array_equal(np.asarray(y), x.reshape(2, 12))

    def test_unsupported_op_clear_error(self):
        nodes = [node_proto("NonMaxSuppression", ["x"], ["y"])]
        blob = model_proto(nodes, [], [value_info("x", (1,))],
                           [value_info("y", (1,))])
        with pytest.raises(OnnxLowerError, match="NonMaxSuppression"):
            _Lowering(read_onnx(blob))

    def test_data_dependent_shape_clear_error(self):
        # Reshape target computed from runtime DATA (not shapes) must be
        # rejected, not silently mis-traced
        nodes = [
            node_proto("Cast", ["x"], ["xi"], to=7),
            node_proto("Reshape", ["x", "xi"], ["y"]),
        ]
        blob = model_proto(nodes, [], [value_info("x", (2,))],
                           [value_info("y", (2,))])
        fn = lower_onnx(read_onnx(blob), jit=False)
        with pytest.raises(OnnxLowerError, match="statically known"):
            fn(np.ones(2, np.float32))


# -- backend -----------------------------------------------------------------

class TestOnnxBackend:
    @pytest.fixture()
    def mlp_file(self, tmp_path):
        blob, _ = build_mlp()
        p = tmp_path / "mlp.onnx"
        p.write_bytes(blob)
        return str(p)

    def test_framework_auto_pipeline(self, mlp_file):
        from nnstreamer_tpu.elements.filter import detect_framework
        from nnstreamer_tpu.pipeline import parse_pipeline

        assert detect_framework(mlp_file) == "onnx"
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=auto "
            f"model={mlp_file} ! tensor_sink name=out"
        )
        pipe.start()
        for _ in range(3):
            pipe["src"].push(np.ones((1, 8), np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
        pipe.stop()
        assert len(outs) == 3 and outs[0].shape == (1, 4)
        np.testing.assert_allclose(outs[0].sum(), 1.0, rtol=1e-5)

    def test_invoke_batch_vmaps(self, mlp_file):
        from nnstreamer_tpu.backends.onnx_import import OnnxBackend

        be = OnnxBackend()
        be.open(mlp_file, {})
        try:
            xs = np.random.default_rng(4).standard_normal(
                (6, 1, 8)).astype(np.float32)
            (out,) = be.invoke_batch([xs])
            out = np.asarray(out)
            assert out.shape == (6, 1, 4)
            for i in range(6):
                (want,) = be.invoke([xs[i]])
                np.testing.assert_allclose(out[i], np.asarray(want),
                                           rtol=1e-5, atol=1e-6)
        finally:
            be.close()

    def test_model_info(self, mlp_file):
        from nnstreamer_tpu.backends.onnx_import import OnnxBackend

        be = OnnxBackend()
        be.open(mlp_file, {})
        try:
            in_spec, out_spec = be.get_model_info()
            assert in_spec.tensors[0].shape == (1, 8)
            assert out_spec.tensors[0].shape == (1, 4)
        finally:
            be.close()


class TestFixedPaths:
    def test_auto_pad_valid_is_zero_padding(self):
        import torch
        import torch.nn.functional as F

        rng = np.random.default_rng(5)
        w = rng.standard_normal((4, 3, 3, 3), np.float32)
        nodes = [node_proto("Conv", ["x", "w"], ["y"],
                            kernel_shape=[3, 3], strides=[1, 1],
                            auto_pad=b"VALID")]
        blob = model_proto(nodes, [tensor_proto("w", w)],
                           [value_info("x", (1, 3, 5, 5))],
                           [value_info("y", (1, 4, 3, 3))])
        fn = lower_onnx(read_onnx(blob))
        x = rng.standard_normal((1, 3, 5, 5)).astype(np.float32)
        (y,) = fn(x)
        assert np.asarray(y).shape == (1, 4, 3, 3)  # not SAME's 5x5
        want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w)).numpy()
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4,
                                   atol=1e-4)

    @pytest.mark.parametrize("style", ["attr", "input1"])
    def test_upsample_scales(self, style):
        if style == "attr":  # Upsample-7
            nodes = [node_proto("Upsample", ["x"], ["y"],
                                mode=b"nearest",
                                scales=[1.0, 1.0, 2.0, 2.0])]
            inits = []
        else:                # Upsample-9 / Resize-10: scales at inputs[1]
            nodes = [node_proto("Upsample", ["x", "sc"], ["y"],
                                mode=b"nearest")]
            inits = [tensor_proto(
                "sc", np.asarray([1.0, 1.0, 2.0, 2.0], np.float32))]
        blob = model_proto(nodes, inits,
                           [value_info("x", (1, 2, 3, 3))],
                           [value_info("y", (1, 2, 6, 6))])
        fn = lower_onnx(read_onnx(blob))
        x = np.arange(18, dtype=np.float32).reshape(1, 2, 3, 3)
        (y,) = fn(x)
        y = np.asarray(y)
        assert y.shape == (1, 2, 6, 6)
        np.testing.assert_array_equal(y, x.repeat(2, 2).repeat(2, 3))


class TestQDQ:
    """QuantizeLinear/DequantizeLinear — the QDQ pattern quantization-
    aware exporters emit around float ops."""

    def test_qdq_roundtrip_on_grid(self):
        # x -> Q(s=0.5, zp=10, uint8) -> DQ -> y: on-grid values survive
        nodes = [
            node_proto("QuantizeLinear", ["x", "s", "zp"], ["q"]),
            node_proto("DequantizeLinear", ["q", "s", "zp"], ["y"]),
        ]
        inits = [tensor_proto("s", np.asarray(0.5, np.float32)),
                 tensor_proto("zp", np.asarray(10, np.uint8))]
        blob = model_proto(nodes, inits, [value_info("x", (8,))],
                           [value_info("y", (8,))])
        fn = lower_onnx(read_onnx(blob))
        xs = ((np.arange(8) * 30) - 5 + 0.0).astype(np.float32) * 0.5
        (y,) = fn(xs)
        want = (np.clip(np.round(xs / 0.5 + 10), 0, 255) - 10) * 0.5
        np.testing.assert_allclose(np.asarray(y), want, atol=1e-6)

    def test_qdq_conv_sandwich(self):
        """DQ(weights) + QDQ activations around a Conv — the standard
        quantized-onnx graph shape — matches the float conv on the
        dequantized operands."""
        import torch
        import torch.nn.functional as F

        rng = np.random.default_rng(11)
        q_w = rng.integers(0, 255, (4, 3, 3, 3)).astype(np.uint8)
        s_w, zp_w = np.float32(0.03), np.uint8(128)
        nodes = [
            node_proto("DequantizeLinear", ["qw", "sw", "zpw"], ["w"]),
            node_proto("Conv", ["x", "w"], ["y"],
                       kernel_shape=[3, 3], strides=[1, 1],
                       pads=[1, 1, 1, 1]),
        ]
        inits = [tensor_proto("qw", q_w),
                 tensor_proto("sw", np.asarray(s_w)),
                 tensor_proto("zpw", np.asarray(zp_w))]
        blob = model_proto(nodes, inits,
                           [value_info("x", (1, 3, 8, 8))],
                           [value_info("y", (1, 4, 8, 8))])
        fn = lower_onnx(read_onnx(blob))
        x = rng.standard_normal((1, 3, 8, 8)).astype(np.float32)
        (y,) = fn(x)
        w_real = (q_w.astype(np.float32) - 128) * 0.03
        want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w_real),
                        padding=1).numpy()
        np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4,
                                   atol=1e-4)


class TestTransformerBlock:
    """A single-head attention + LayerNorm + Gelu MLP block — the
    transformer op subset (MatMul/Transpose/Softmax/LayerNormalization/
    Erf-Gelu/Add) cross-checked against torch."""

    def test_attention_block_matches_torch(self):
        import torch

        rng = np.random.default_rng(12)
        T, D = 5, 8
        wq = rng.standard_normal((D, D), np.float32) * 0.3
        wk = rng.standard_normal((D, D), np.float32) * 0.3
        wv = rng.standard_normal((D, D), np.float32) * 0.3
        g = (rng.random(D).astype(np.float32) + 0.5)
        b = rng.standard_normal(D).astype(np.float32) * 0.1
        w1 = rng.standard_normal((D, 2 * D), np.float32) * 0.3
        scale = np.float32(1.0 / np.sqrt(D))
        inv_sqrt2 = np.float32(1.0 / np.sqrt(2.0))

        nodes = [
            # LayerNorm(x)
            node_proto("LayerNormalization", ["x", "g", "b"], ["ln"],
                       axis=-1, epsilon=1e-5),
            # q,k,v projections
            node_proto("MatMul", ["ln", "wq"], ["q"]),
            node_proto("MatMul", ["ln", "wk"], ["k"]),
            node_proto("MatMul", ["ln", "wv"], ["v"]),
            # scores = softmax(q @ k^T / sqrt(D))
            node_proto("Transpose", ["k"], ["kT"], perm=[1, 0]),
            node_proto("MatMul", ["q", "kT"], ["qk"]),
            node_proto("Mul", ["qk", "scale"], ["qks"]),
            node_proto("Softmax", ["qks"], ["att"], axis=-1),
            node_proto("MatMul", ["att", "v"], ["ctx"]),
            # residual + exact GELU MLP (x * 0.5 * (1 + erf(x/sqrt(2))))
            node_proto("Add", ["x", "ctx"], ["res"]),
            node_proto("MatMul", ["res", "w1"], ["h"]),
            node_proto("Mul", ["h", "inv_sqrt2"], ["h_s"]),
            node_proto("Erf", ["h_s"], ["h_erf"]),
            node_proto("Add", ["h_erf", "one"], ["h_1p"]),
            node_proto("Mul", ["h", "h_1p"], ["h_m"]),
            node_proto("Mul", ["h_m", "half"], ["y"]),
        ]
        inits = [tensor_proto(n, a) for n, a in [
            ("wq", wq), ("wk", wk), ("wv", wv), ("g", g), ("b", b),
            ("w1", w1), ("scale", np.asarray(scale)),
            ("inv_sqrt2", np.asarray(inv_sqrt2)),
            ("one", np.asarray(np.float32(1.0))),
            ("half", np.asarray(np.float32(0.5)))]]
        blob = model_proto(nodes, inits,
                           [value_info("x", (T, D))],
                           [value_info("y", (T, 2 * D))],
                           opset=17)  # LayerNormalization needs >= 17
        fn = lower_onnx(read_onnx(blob))
        x = rng.standard_normal((T, D)).astype(np.float32)
        (y,) = fn(x)

        xt = torch.from_numpy(x)
        ln = torch.nn.functional.layer_norm(
            xt, (D,), torch.from_numpy(g), torch.from_numpy(b), eps=1e-5)
        q = ln @ torch.from_numpy(wq)
        k = ln @ torch.from_numpy(wk)
        v = ln @ torch.from_numpy(wv)
        att = torch.softmax(q @ k.T * float(scale), dim=-1)
        res = xt + att @ v
        h = res @ torch.from_numpy(w1)
        want = (h * 0.5 * (1 + torch.erf(h / np.sqrt(2.0)))).numpy()
        np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4,
                                   atol=2e-4)


class TestParserRobustness:
    """Untrusted model bytes must raise parse errors — never crash,
    hang, or allocate absurdly (model files cross trust boundaries:
    the query/edge elements accept remote peers)."""

    def test_fuzz_onnx_reader(self):
        rng = np.random.default_rng(0)
        blob, _ = build_mlp()
        for _ in range(300):
            buf = bytearray(blob)
            for _ in range(rng.integers(1, 12)):
                buf[rng.integers(0, len(buf))] = rng.integers(0, 256)
            try:
                m = read_onnx(bytes(buf))
                # parsed despite mutation: lowering may reject it, but
                # must do so with a typed error
                try:
                    _Lowering(m)
                except Exception:
                    pass  # lowering may reject; must not hang/crash
            except OnnxParseError:
                pass  # the ONLY exception type allowed to escape

    def test_fuzz_random_bytes(self):
        rng = np.random.default_rng(1)
        for n in (0, 1, 7, 64, 512):
            with pytest.raises(OnnxParseError):
                read_onnx(bytes(rng.integers(0, 256, n, dtype=np.uint8)))
