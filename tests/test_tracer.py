"""Pipeline tracer: the GstShark-analog proctime/interlatency/framerate/
queuelevel/bitrate measurements (SURVEY §5.1; reference delegates these to
GstShark tracer hooks, ``tools/tracing/README.md``)."""

import numpy as np

from nnstreamer_tpu.pipeline import parse_pipeline


def _run_traced(n_frames=32, detail=False):
    # fuse=False: queue-level tracing samples mailboxes, which only exist
    # at thread boundaries — the unfused dataplane gives every element one
    # (fused chains have no intermediate queues to sample, by design)
    pipe = parse_pipeline(
        "appsrc name=src ! "
        "tensor_transform mode=arithmetic option=add:1.0 ! "
        "tensor_sink name=out max-stored=64",
        name="traced",
        fuse=False,
    )
    tracer = pipe.enable_tracing(detail=detail)
    pipe.start()
    src = pipe["src"]
    for i in range(n_frames):
        src.push(np.full((4, 4), float(i), np.float32))
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    return tracer, n_frames


def test_tracer_counts_and_latency():
    tracer, n = _run_traced()
    rep = tracer.report()
    # the transform and the sink both processed every frame
    els = {name: r for name, r in rep.items()}
    transform = next(r for name, r in els.items() if "transform" in name)
    sink = els["out"]
    assert transform["frames"] == n
    assert sink["frames"] == n
    # proctime measured and sane (>0, < 1s)
    assert 0 < transform["proctime_us_avg"] < 1e6
    assert transform["proctime_us_p99"] >= transform["proctime_us_p50"]
    # interlatency: frames carried a source stamp through the chain
    assert transform["interlatency_ms_avg"] is not None
    assert sink["interlatency_ms_avg"] >= 0
    # bitrate: 4x4 float32 = 64 bytes per frame flowed
    assert transform["bitrate_mbps"] >= 0
    # queue levels sampled with a real capacity
    assert sink["queue_capacity"] > 0
    # scheduletime: inter-dequeue gap measured after the first call
    assert transform["scheduletime_us_avg"] is not None
    assert transform["scheduletime_us_avg"] > 0
    assert tracer.cpu_usage() >= 0.0


def test_tracer_summary_renders():
    tracer, _ = _run_traced(8)
    lines = tracer.summary_lines()
    assert len(lines) >= 3  # header + 2 elements
    assert "fps" in lines[0] and "inter ms" in lines[0]


def test_chrome_trace_export(tmp_path):
    import json

    tracer, n = _run_traced(16, detail=True)
    path = str(tmp_path / "trace.json")
    tracer.export_chrome_trace(path)
    with open(path) as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    spans = [e for e in events if e["ph"] == "X"]
    # detail mode: one real span per element call, with timestamps
    assert len(spans) >= 2 * n
    assert all(e["dur"] > 0 for e in spans)
    names = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert any("out" == nm for nm in names)
    assert any(e["ph"] == "C" for e in events)  # fps counters


def test_no_tracer_by_default():
    pipe = parse_pipeline(
        "appsrc name=src ! tensor_sink name=out", name="untraced"
    )
    assert pipe.tracer is None
    pipe.start()
    pipe["src"].push(np.zeros((2,), np.float32))
    pipe["src"].end_of_stream()
    pipe.wait(timeout=10)
    pipe.stop()
