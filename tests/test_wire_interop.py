"""Protobuf wire-IDL interop (≙ reference nnstreamer.proto +
ext/nnstreamer/extra/nnstreamer_grpc_protobuf.cc round-trip coverage).

The key property: a NON-framework peer speaking only google.protobuf and
the checked-in schema can exchange frames with the framework — proven by
building/parsing messages with the raw generated classes on one side and
the framework codec on the other.
"""

import math

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.distributed import protobuf_codec, wire
from nnstreamer_tpu.pipeline import parse_pipeline


class TestCodecRoundtrip:
    @pytest.mark.parametrize(
        "dtype",
        ["uint8", "int8", "int16", "uint16", "int32", "uint32",
         "int64", "uint64", "float16", "float32", "float64"],
    )
    def test_all_dtypes(self, dtype):
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 100, (3, 4)).astype(dtype)
        frame = TensorFrame([arr], pts=2.25, meta={"k": "v"})
        out = protobuf_codec.decode_frame(protobuf_codec.encode_frame(frame))
        np.testing.assert_array_equal(out.tensors[0], arr)
        assert out.tensors[0].dtype == np.dtype(dtype)
        assert out.pts == 2.25
        assert out.meta["k"] == "v"
        assert out.seq == frame.seq

    def test_bfloat16(self):
        import ml_dtypes

        arr = np.arange(6, dtype=np.float32).reshape(2, 3).astype(ml_dtypes.bfloat16)
        out = protobuf_codec.decode_frame(
            protobuf_codec.encode_frame(TensorFrame([arr]))
        )
        np.testing.assert_array_equal(
            out.tensors[0].astype(np.float32), arr.astype(np.float32)
        )

    def test_multi_tensor_and_no_pts(self):
        frame = TensorFrame([np.zeros((2,), np.uint8), np.ones((1, 1), np.float32)])
        out = protobuf_codec.decode_frame(protobuf_codec.encode_frame(frame))
        assert len(out.tensors) == 2
        assert out.pts is None

    def test_malformed_raises_wire_error(self):
        # a parseable protobuf whose payload length contradicts its shape
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        bad = pb.TensorFrame(
            num_tensors=1,
            tensor=[pb.Tensor(type=7, dimension=[4], data=b"\x00" * 3)],
            pts=math.nan,
        )
        with pytest.raises(wire.WireError, match="payload"):
            protobuf_codec.decode_frame(bad.SerializeToString())

    def test_get_codec_registry(self):
        assert wire.get_codec("flex") == (wire.encode_frame, wire.decode_frame)
        enc, dec = wire.get_codec("protobuf")
        assert enc is protobuf_codec.encode_frame
        with pytest.raises(wire.WireError, match="unknown wire idl"):
            wire.get_codec("capnproto")


class TestExternalPeer:
    """A peer that never imports nnstreamer_tpu — just the generated pb2."""

    def test_external_producer_framework_consumer(self):
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        msg = pb.TensorFrame(
            num_tensors=1,
            tensor=[pb.Tensor(
                name="ext", type=7,  # FLOAT32
                dimension=[3, 4], data=arr.tobytes(),
            )],
            pts=1.0,
            meta_json='{"origin": "external"}',
        )
        frame = protobuf_codec.decode_frame(msg.SerializeToString())
        np.testing.assert_array_equal(frame.tensors[0], arr)
        assert frame.meta["origin"] == "external"

    def test_framework_producer_external_consumer(self):
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        arr = np.full((2, 2), 7, np.int32)
        raw = protobuf_codec.encode_frame(TensorFrame([arr], pts=0.5))
        msg = pb.TensorFrame()
        msg.ParseFromString(raw)
        assert msg.num_tensors == 1
        assert list(msg.tensor[0].dimension) == [2, 2]
        assert msg.tensor[0].type == 0  # INT32
        got = np.frombuffer(msg.tensor[0].data, np.int32).reshape(2, 2)
        np.testing.assert_array_equal(got, arr)


class TestPipelinesOverProtobufIdl:
    def test_grpc_stream_idl_protobuf(self):
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=2 "
            "idl=protobuf timeout=15000 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} "
            "idl=protobuf"
        )
        tx.start()
        for i in range(2):
            tx["a"].push(np.full((2,), i, np.int64), pts=float(i))
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        rx.wait(timeout=30)
        tx.stop()
        frames = rx["out"].frames
        rx.stop()
        assert len(frames) == 2
        np.testing.assert_array_equal(frames[1].tensors[0], np.full((2,), 1, np.int64))
        assert frames[1].pts == pytest.approx(1.0)

    def test_idl_mismatch_drops_frames(self):
        # flex sender -> protobuf receiver: undecodable frames are dropped,
        # the stream times out to EOS instead of corrupting data
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=1 "
            "idl=protobuf timeout=1500 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} idl=flex"
        )
        tx.start()
        tx["a"].push(np.zeros((2,), np.uint8))
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        rx.wait(timeout=20)
        tx.stop()
        frames = rx["out"].frames
        rx.stop()
        assert frames == []
