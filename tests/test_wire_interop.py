"""Protobuf wire-IDL interop (≙ reference nnstreamer.proto +
ext/nnstreamer/extra/nnstreamer_grpc_protobuf.cc round-trip coverage).

The key property: a NON-framework peer speaking only google.protobuf and
the checked-in schema can exchange frames with the framework — proven by
building/parsing messages with the raw generated classes on one side and
the framework codec on the other.
"""

import math

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.distributed import protobuf_codec, wire
from nnstreamer_tpu.pipeline import parse_pipeline


class TestCodecRoundtrip:
    @pytest.mark.parametrize(
        "dtype",
        ["uint8", "int8", "int16", "uint16", "int32", "uint32",
         "int64", "uint64", "float16", "float32", "float64"],
    )
    def test_all_dtypes(self, dtype):
        rng = np.random.default_rng(0)
        arr = rng.integers(0, 100, (3, 4)).astype(dtype)
        frame = TensorFrame([arr], pts=2.25, meta={"k": "v"})
        out = protobuf_codec.decode_frame(protobuf_codec.encode_frame(frame))
        np.testing.assert_array_equal(out.tensors[0], arr)
        assert out.tensors[0].dtype == np.dtype(dtype)
        assert out.pts == 2.25
        assert out.meta["k"] == "v"
        assert out.seq == frame.seq

    def test_bfloat16(self):
        import ml_dtypes

        arr = np.arange(6, dtype=np.float32).reshape(2, 3).astype(ml_dtypes.bfloat16)
        out = protobuf_codec.decode_frame(
            protobuf_codec.encode_frame(TensorFrame([arr]))
        )
        np.testing.assert_array_equal(
            out.tensors[0].astype(np.float32), arr.astype(np.float32)
        )

    def test_multi_tensor_and_no_pts(self):
        frame = TensorFrame([np.zeros((2,), np.uint8), np.ones((1, 1), np.float32)])
        out = protobuf_codec.decode_frame(protobuf_codec.encode_frame(frame))
        assert len(out.tensors) == 2
        assert out.pts is None

    def test_malformed_raises_wire_error(self):
        # a parseable protobuf whose payload length contradicts its shape
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        bad = pb.TensorFrame(
            num_tensors=1,
            tensor=[pb.Tensor(type=7, dimension=[4], data=b"\x00" * 3)],
            pts=math.nan,
        )
        with pytest.raises(wire.WireError, match="payload"):
            protobuf_codec.decode_frame(bad.SerializeToString())

    def test_get_codec_registry(self):
        assert wire.get_codec("flex") == (wire.encode_frame, wire.decode_frame)
        enc, dec = wire.get_codec("protobuf")
        assert enc is protobuf_codec.encode_frame
        with pytest.raises(wire.WireError, match="unknown wire idl"):
            wire.get_codec("capnproto")


class TestExternalPeer:
    """A peer that never imports nnstreamer_tpu — just the generated pb2."""

    def test_external_producer_framework_consumer(self):
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        msg = pb.TensorFrame(
            num_tensors=1,
            tensor=[pb.Tensor(
                name="ext", type=7,  # FLOAT32
                dimension=[3, 4], data=arr.tobytes(),
            )],
            pts=1.0,
            meta_json='{"origin": "external"}',
        )
        frame = protobuf_codec.decode_frame(msg.SerializeToString())
        np.testing.assert_array_equal(frame.tensors[0], arr)
        assert frame.meta["origin"] == "external"

    def test_framework_producer_external_consumer(self):
        from nnstreamer_tpu.distributed.proto import nns_tensors_pb2 as pb

        arr = np.full((2, 2), 7, np.int32)
        raw = protobuf_codec.encode_frame(TensorFrame([arr], pts=0.5))
        msg = pb.TensorFrame()
        msg.ParseFromString(raw)
        assert msg.num_tensors == 1
        assert list(msg.tensor[0].dimension) == [2, 2]
        assert msg.tensor[0].type == 0  # INT32
        got = np.frombuffer(msg.tensor[0].data, np.int32).reshape(2, 2)
        np.testing.assert_array_equal(got, arr)


class TestFlatbufCodec:
    """FlatBuffers wire-IDL interop (≙ reference nnstreamer.fbs +
    ext/nnstreamer/tensor_decoder/tensordec-flatbuf.cc).

    The key property: the emitted bytes follow the *standard* FlatBuffers
    binary layout for the reference schema, so a peer that ran flatc over
    nnstreamer.fbs parses them unmodified.  Proven two ways: (a) decode
    with the stock ``flatbuffers`` runtime's generic Table accessors (what
    flatc-generated readers compile down to), and (b) a hand-rolled
    ``struct``-only walk of the binary — no flatbuffers import at all —
    checking root offset, vtable indirection, and field payloads.
    """

    @pytest.mark.parametrize(
        "dtype",
        ["uint8", "int8", "int16", "uint16", "int32", "uint32",
         "int64", "uint64", "float32", "float64"],
    )
    def test_all_fbs_dtypes(self, dtype):
        from nnstreamer_tpu.distributed import flatbuf_codec

        rng = np.random.default_rng(3)
        arr = rng.integers(0, 100, (2, 3, 4)).astype(dtype)
        out = flatbuf_codec.decode_frame(
            flatbuf_codec.encode_frame(TensorFrame([arr]))
        )
        np.testing.assert_array_equal(out.tensors[0], arr)
        assert out.tensors[0].dtype == np.dtype(dtype)

    def test_unrepresentable_dtype_raises(self):
        from nnstreamer_tpu.distributed import flatbuf_codec

        with pytest.raises(wire.WireError, match="not representable"):
            flatbuf_codec.encode_frame(
                TensorFrame([np.zeros((2,), np.float16)])
            )

    def test_zero_size_tensor_rejected_at_encode(self):
        # 0 is the wire's dimension terminator: a zero-size tensor would
        # misparse on any stock peer, so encode must refuse it up front
        from nnstreamer_tpu.distributed import flatbuf_codec

        for shape in ((0,), (0, 3), (2, 0)):
            with pytest.raises(wire.WireError, match="zero-size"):
                flatbuf_codec.encode_frame(
                    TensorFrame([np.zeros(shape, np.float32)])
                )

    def test_multi_tensor_and_framerate(self):
        from nnstreamer_tpu.distributed import flatbuf_codec

        frame = TensorFrame(
            [np.zeros((2,), np.uint8), np.ones((1, 5), np.float32)],
            meta={"framerate": [30, 1]},
        )
        out = flatbuf_codec.decode_frame(flatbuf_codec.encode_frame(frame))
        assert len(out.tensors) == 2
        assert out.meta["framerate"] == [30, 1]

    def test_payload_shape_mismatch_raises(self):
        import flatbuffers

        from nnstreamer_tpu.distributed import flatbuf_codec

        b = flatbuffers.Builder(64)
        dim_off = b.CreateNumpyVector(
            np.asarray([4] + [0] * 15, np.uint32))
        data_off = b.CreateByteVector(b"\x00" * 3)  # 3B for 4 x uint8
        b.StartObject(4)
        b.PrependInt32Slot(1, 5, 10)  # NNS_UINT8
        b.PrependUOffsetTRelativeSlot(2, dim_off, 0)
        b.PrependUOffsetTRelativeSlot(3, data_off, 0)
        t = b.EndObject()
        b.StartVector(4, 1, 4)
        b.PrependUOffsetTRelative(t)
        vec = b.EndVector()
        b.StartObject(4)
        b.PrependInt32Slot(0, 1, 0)
        b.PrependUOffsetTRelativeSlot(2, vec, 0)
        b.Finish(b.EndObject())
        with pytest.raises(wire.WireError, match="payload"):
            flatbuf_codec.decode_frame(bytes(b.Output()))

    def test_external_producer_framework_consumer(self):
        # a peer using only the flatbuffers runtime + the schema's layout
        import flatbuffers

        from nnstreamer_tpu.distributed import flatbuf_codec

        arr = np.arange(6, dtype=np.int32).reshape(2, 3)
        b = flatbuffers.Builder(256)
        name = b.CreateString("ext")
        # innermost-first, rank-16 zero-padded (reference dialect)
        dim = b.CreateNumpyVector(
            np.asarray([3, 2] + [0] * 14, np.uint32))
        data = b.CreateByteVector(arr.tobytes())
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name, 0)
        b.PrependInt32Slot(1, 0, 10)  # NNS_INT32
        b.PrependUOffsetTRelativeSlot(2, dim, 0)
        b.PrependUOffsetTRelativeSlot(3, data, 0)
        t = b.EndObject()
        b.StartVector(4, 1, 4)
        b.PrependUOffsetTRelative(t)
        vec = b.EndVector()
        b.StartObject(4)
        b.PrependInt32Slot(0, 1, 0)
        b.Prep(4, 8)
        b.PrependInt32(1)   # rate_d
        b.PrependInt32(30)  # rate_n
        b.PrependStructSlot(1, b.Offset(), 0)
        b.PrependUOffsetTRelativeSlot(2, vec, 0)
        b.PrependInt32Slot(3, 0, 0)
        b.Finish(b.EndObject())
        frame = flatbuf_codec.decode_frame(bytes(b.Output()))
        np.testing.assert_array_equal(frame.tensors[0], arr)
        assert frame.meta["framerate"] == [30, 1]
        assert frame.meta["tensor_name"] == "ext"

    @staticmethod
    def _raw_u32(buf, off):
        import struct

        return struct.unpack_from("<I", buf, off)[0]

    @staticmethod
    def _raw_i32(buf, off):
        import struct

        return struct.unpack_from("<i", buf, off)[0]

    @staticmethod
    def _raw_field(buf, table_pos, slot):
        """Standard FlatBuffers field lookup with struct only: soffset to
        vtable, then the slot's in-table offset (0 = absent)."""
        import struct

        vtab = table_pos - struct.unpack_from("<i", buf, table_pos)[0]
        vsize = struct.unpack_from("<H", buf, vtab)[0]
        fo = 4 + 2 * slot
        if fo >= vsize:
            return 0
        rel = struct.unpack_from("<H", buf, vtab + fo)[0]
        return table_pos + rel if rel else 0

    def test_framework_producer_raw_binary_consumer(self):
        """Walk the emitted buffer with struct only — an independent
        implementation of the FlatBuffers wire format, so a shared bug in
        encoder+decoder can't fake a pass."""
        from nnstreamer_tpu.distributed import flatbuf_codec

        arr = np.arange(8, dtype=np.float32).reshape(2, 4)
        buf = flatbuf_codec.encode_frame(
            TensorFrame([arr], meta={"framerate": [15, 2]})
        )
        root = self._raw_u32(buf, 0)
        # Tensors.num_tensor (slot 0)
        p = self._raw_field(buf, root, 0)
        assert p and self._raw_i32(buf, p) == 1
        # Tensors.fr struct (slot 1): rate_n, rate_d inline
        p = self._raw_field(buf, root, 1)
        assert p and (self._raw_i32(buf, p),
                      self._raw_i32(buf, p + 4)) == (15, 2)
        # Tensors.format (slot 3) = STATIC
        p = self._raw_field(buf, root, 3)
        assert self._raw_i32(buf, p) == 0 if p else True
        # Tensors.tensor vector (slot 2) -> one Tensor table
        p = self._raw_field(buf, root, 2)
        assert p
        vec = p + self._raw_u32(buf, p)
        assert self._raw_u32(buf, vec) == 1  # vector length
        elem = vec + 4
        tpos = elem + self._raw_u32(buf, elem)  # table indirection
        # Tensor.type (slot 1) = NNS_FLOAT32 (7)
        tp = self._raw_field(buf, tpos, 1)
        assert tp and self._raw_i32(buf, tp) == 7
        # Tensor.dimension (slot 2): rank-16 uint32, innermost-first
        dp = self._raw_field(buf, tpos, 2)
        assert dp
        dvec = dp + self._raw_u32(buf, dp)
        assert self._raw_u32(buf, dvec) == 16
        dims = [self._raw_u32(buf, dvec + 4 + 4 * i) for i in range(16)]
        assert dims == [4, 2] + [0] * 14
        # Tensor.data (slot 3): raw little-endian float payload
        pp = self._raw_field(buf, tpos, 3)
        assert pp
        pvec = pp + self._raw_u32(buf, pp)
        n = self._raw_u32(buf, pvec)
        assert n == arr.nbytes
        got = np.frombuffer(buf, np.float32, count=8, offset=pvec + 4)
        np.testing.assert_array_equal(got.reshape(2, 4), arr)

    def test_decoder_converter_subplugins_roundtrip(self):
        # tensor_decoder mode=flatbuf ! tensor_converter mode=flatbuf is
        # an identity pipeline speaking the reference schema in between
        pipe = parse_pipeline(
            "appsrc name=a ! tensor_decoder mode=flatbuf ! "
            "tensor_converter mode=custom:flatbuf ! tensor_sink name=out"
        )
        pipe.start()
        arr = np.arange(10, dtype=np.uint8).reshape(2, 5)
        pipe["a"].push(arr)
        pipe["a"].end_of_stream()
        pipe.wait(timeout=20)
        frames = pipe["out"].frames
        pipe.stop()
        assert len(frames) == 1
        np.testing.assert_array_equal(frames[0].tensors[0], arr)

    def test_get_codec_flatbuf(self):
        from nnstreamer_tpu.distributed import flatbuf_codec

        enc, dec = wire.get_codec("flatbuf")
        assert enc is flatbuf_codec.encode_frame
        assert dec is flatbuf_codec.decode_frame


class TestPipelinesOverProtobufIdl:
    def test_grpc_stream_idl_protobuf(self):
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=2 "
            "idl=protobuf timeout=15000 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} "
            "idl=protobuf"
        )
        tx.start()
        for i in range(2):
            tx["a"].push(np.full((2,), i, np.int64), pts=float(i))
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        rx.wait(timeout=30)
        tx.stop()
        frames = rx["out"].frames
        rx.stop()
        assert len(frames) == 2
        np.testing.assert_array_equal(frames[1].tensors[0], np.full((2,), 1, np.int64))
        assert frames[1].pts == pytest.approx(1.0)

    def test_grpc_stream_idl_flatbuf(self):
        # streaming over the reference's actual flatbuffers schema; the
        # schema has no pts field, so timestamps don't survive (reference
        # parity: its flatbuf path drops GstBuffer metadata too)
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=2 "
            "idl=flatbuf timeout=15000 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} "
            "idl=flatbuf"
        )
        tx.start()
        for i in range(2):
            tx["a"].push(np.full((3,), i, np.float32))
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        rx.wait(timeout=30)
        tx.stop()
        frames = rx["out"].frames
        rx.stop()
        assert len(frames) == 2
        np.testing.assert_array_equal(
            frames[1].tensors[0], np.full((3,), 1, np.float32))

    def test_idl_mismatch_drops_frames(self):
        # flex sender -> protobuf receiver: undecodable frames are dropped,
        # the stream times out to EOS instead of corrupting data
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=1 "
            "idl=protobuf timeout=1500 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} idl=flex"
        )
        tx.start()
        tx["a"].push(np.zeros((2,), np.uint8))
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        rx.wait(timeout=20)
        tx.stop()
        frames = rx["out"].frames
        rx.stop()
        assert frames == []


class TestDecodeAliasingContract:
    """decode_frame tensors are zero-copy views over the receive buffer.
    The writability contract is explicit: views are READ-ONLY, so an
    in-place downstream transform can never silently corrupt a pooled or
    reused receive buffer — it must copy first (numpy raises on writes)."""

    def _frame_bytes(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        return arr, bytes(wire.encode_frame(TensorFrame([arr], pts=0.5)))

    def test_views_are_read_only_even_over_writable_buffers(self):
        arr, buf = self._frame_bytes()
        # a pooled/reused receive buffer is WRITABLE (bytearray); the
        # decoded views must still refuse writes
        pooled = bytearray(buf)
        out = wire.decode_frame(pooled)
        assert not out.tensors[0].flags.writeable
        with pytest.raises(ValueError):
            out.tensors[0][0, 0] = 99.0
        np.testing.assert_array_equal(out.tensors[0], arr)

    def test_view_aliases_buffer_not_copy(self):
        arr, buf = self._frame_bytes()
        pooled = bytearray(buf)
        out = wire.decode_frame(pooled)
        # zero-copy: the tensor's memory IS the receive buffer
        assert np.shares_memory(
            out.tensors[0], np.frombuffer(pooled, np.uint8)
        )

    def test_downstream_transform_leaves_buffer_intact(self):
        # an arithmetic transform downstream of a decoded frame works
        # (out-of-place) and the receive buffer is bit-identical after
        arr, buf = self._frame_bytes()
        pooled = bytearray(buf)
        before = bytes(pooled)
        decoded = wire.decode_frame(pooled)
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=mul:2 ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(decoded)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=20)
        pipe.stop()
        frames = pipe["out"].frames
        assert len(frames) == 1
        np.testing.assert_array_equal(frames[0].tensors[0], arr * 2)
        assert bytes(pooled) == before  # receive buffer never mutated
