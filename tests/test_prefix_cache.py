"""Shared-prefix KV cache: chain digests, refcounted page pool, slot-
engine attach/publish, element wiring, trim ladder, and the warm-hit
bit-exactness contract (core/continuity.py prefix_digests +
core/slots.py PrefixCache + models/transformer.py export/attach).

Oracles:

* Warm hits MUST be invisible in the token stream: a stream that
  attaches cached prefix pages yields tokens BIT-IDENTICAL to the
  one-shot ``generate:<N>`` path and to a cache-cold run — greedy and
  seeded sampling, fused and unfused.  The cache is a latency
  optimization, never a sampling change.
* Accounting is EXACT: one hit (+hit_tokens) or one miss per eligible
  lookup, publishes = entries stored, evictions = entries reclaimed;
  refcounts pin pages for a stream's whole slot occupancy, so trim and
  LRU overflow can never reclaim under a live reader.
"""

import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.continuity import (
    PREFIX_GRAIN,
    prefix_digests,
    prefix_route_key,
    prompt_digest,
)
from nnstreamer_tpu.core.slots import PrefixCache, SimSlotModel, SlotEngine
from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline

PROPS = {
    "dtype": "float32", "vocab": 61, "d_model": 32, "heads": 2,
    "layers": 2, "d_ff": 64, "seq": 64, "seed": 11,
}
CUSTOM = ",".join(f"{k}:{v}" for k, v in PROPS.items())
SAMPLING = {"temperature": "0.8", "top_k": "7", "gen_seed": "3"}


def _oneshot(prompt, n, extra=None):
    props = {**{k: str(v) for k, v in PROPS.items()}, "generate": str(n)}
    if extra:
        props.update(extra)
    fn, params, _, _ = build("transformer", props)
    return np.asarray(fn(params, [prompt])[0])[:, prompt.shape[1]:]


def _drain(eng, timeout=60.0):
    out, deadline = [], time.monotonic() + timeout
    while time.monotonic() < deadline:
        out.extend(f for _pad, f in eng.pop_ready())
        if out and any(f.meta.get("final") for f in out):
            return out
        eng.wait_progress(0.02)
    raise TimeoutError("engine drain timed out")


def _tokens(frames):
    frames = sorted(frames, key=lambda f: f.meta["chunk_index"])
    parts = [np.asarray(f.tensors[0]) for f in frames if f.tensors]
    return (np.concatenate(parts, axis=1) if parts
            else np.zeros((1, 0), np.int32))


def sim_oracle(vocab, prompt, n):
    t = int(prompt.sum()) % vocab
    out = [t]
    for _ in range(n - 1):
        t = (31 * t + 17) % vocab
        out.append(t)
    return np.asarray([out], np.int32)


# ---------------------------------------------------------------------------
# Chain digests (core/continuity.py)
# ---------------------------------------------------------------------------
class TestPrefixDigests:
    def test_digest_identifies_full_left_context(self):
        """d_i depends on every token left of it, not just chunk i —
        pages from different prefixes can never alias."""
        a = np.arange(200, dtype=np.int32)
        b = a.copy()
        b[70] = 7  # inside chunk 1
        da, db = prefix_digests(a, 64), prefix_digests(b, 64)
        assert len(da) == 3  # trailing partial chunk gets no digest
        assert da[0] == db[0]          # chunk 0 identical
        assert da[1] != db[1]          # chunk 1 differs
        assert da[2] != db[2]          # chunk 2 bytes equal, context not

    def test_grain_changes_every_digest(self):
        a = np.arange(128, dtype=np.int32)
        assert set(prefix_digests(a, 64)).isdisjoint(prefix_digests(a, 32))

    def test_route_key_declared_rounds_down_to_grain(self):
        a = np.arange(300, dtype=np.int32)
        full = prefix_digests(a, PREFIX_GRAIN)
        # declared 200 -> 3 grain chunks (192 tokens) -> chain digest d_2
        assert prefix_route_key(a, declared=200) == full[2]
        # no declaration -> first grain chunk
        assert prefix_route_key(a) == full[0]

    def test_route_key_short_prompt_falls_back_to_prompt_digest(self):
        a = np.arange(10, dtype=np.int32)
        assert prefix_route_key(a) == prompt_digest(a[None])  # (1, Tp) view


# ---------------------------------------------------------------------------
# PrefixCache pool (no engine, no model)
# ---------------------------------------------------------------------------
def _entry(i, tokens=8):
    return f"d{i}", i, {"carry": i, "n": tokens}, tokens


class TestPrefixCachePool:
    def test_publish_acquire_release_exact_accounting(self):
        pc = PrefixCache(grain=8)
        assert pc.publish("d0", 0, {"x": 0}, 8)
        assert pc.publish("d1", 1, {"x": 1}, 8)
        assert not pc.publish("d0", 0, {"x": 9}, 8)  # dup: no-op
        got = pc.acquire(["d0", "d1", "dMISSING"])
        assert [e.digest for e in got] == ["d0", "d1"]
        snap = pc.snapshot()
        assert snap["prefix_hits"] == 1          # ONE hit per lookup
        assert snap["prefix_hit_tokens"] == 16
        assert snap["prefix_publishes"] == 2
        assert snap["prefix_refs"] == 2
        assert pc.acquire(["dX"]) == []
        assert pc.snapshot()["prefix_misses"] == 1
        pc.release(got)
        assert pc.snapshot()["prefix_refs"] == 0

    def test_acquire_stops_at_first_gap(self):
        """Only the longest CONSECUTIVE run from index 0 attaches — a
        mid-chain gap means the pages right of it are unreachable."""
        pc = PrefixCache(grain=8)
        pc.publish("d0", 0, {}, 8)
        pc.publish("d2", 2, {}, 8)  # published under index 2
        got = pc.acquire(["d0", "dGAP", "d2"])
        assert [e.digest for e in got] == ["d0"]
        pc.release(got)

    def test_lru_eviction_skips_pinned_entries(self):
        pc = PrefixCache(grain=8, cap_entries=1)
        pc.publish("d0", 0, {}, 8)
        pinned = pc.acquire(["d0"])
        assert not pc.publish("d1", 0, {}, 8)  # sole entry pinned
        assert pc.snapshot()["prefix_publishes"] == 1
        pc.release(pinned)
        assert pc.publish("d1", 0, {}, 8)      # now d0 is evictable
        snap = pc.snapshot()
        assert snap["prefix_evictions"] == 1
        assert snap["prefix_entries"] == 1
        assert not pc.contains("d0") and pc.contains("d1")

    def test_trim_reclaims_only_unpinned(self):
        pc = PrefixCache(grain=8)
        for i in range(4):
            pc.publish(*_entry(i))
        pinned = pc.acquire(["d0", "d1"])
        assert pc.trim() == 2                  # d2, d3 only
        assert pc.contains("d0") and pc.contains("d1")
        pc.release(pinned)
        assert pc.trim() == 2
        snap = pc.snapshot()
        assert snap["prefix_entries"] == 0
        assert snap["prefix_evictions"] == 4

    def test_byte_cap_and_clear(self):
        pc = PrefixCache(grain=8, cap_bytes=100)
        big = np.zeros(20, np.int32)  # 80 bytes
        pc.publish("d0", 0, {"p": big}, 8)
        pc.publish("d1", 0, {"p": big}, 8)  # over 100B: d0 evicted
        snap = pc.snapshot()
        assert snap["prefix_entries"] == 1 and snap["prefix_evictions"] == 1
        assert snap["prefix_bytes"] == 80
        pc.clear()
        snap = pc.snapshot()
        assert snap["prefix_entries"] == 0 and snap["prefix_bytes"] == 0
        assert snap["prefix_evictions"] == 2

    def test_hot_digests_mru_order(self):
        pc = PrefixCache(grain=8)
        for i in range(3):
            pc.publish(*_entry(i))
        pc.release(pc.acquire(["d0"]))
        hot = pc.hot_digests()
        assert hot[0] == "d0"[:12] and len(hot) == 3


# ---------------------------------------------------------------------------
# Engine integration (sim model — fast, exact counters)
# ---------------------------------------------------------------------------
def _sim_engine(pool, slots=1, step_ms=0.05, **kw):
    model = SimSlotModel(slots, step_base_ms=step_ms,
                         prefill_ms_per_token=0.01)
    eng = SlotEngine(model, None, max_seq=1 << 20, chunk=4,
                     prefill_chunk=4, prefix_cache=pool, **kw)
    eng.start()
    return eng, model


class TestEnginePrefix:
    def test_grain_off_prefill_grid_refused(self):
        with pytest.raises(ValueError, match="multiple"):
            SlotEngine(SimSlotModel(1), None, max_seq=64,
                       prefill_chunk=4, prefix_cache=PrefixCache(grain=6))

    def test_shared_prefix_hit_exact_counters_and_tokens(self):
        pool = PrefixCache(grain=8)
        eng, model = _sim_engine(pool)
        try:
            p1 = np.arange(17, dtype=np.int32)[None]
            p2 = p1.copy()
            p2[0, 16] = 55  # same 16-token prefix, different tail
            eng.submit(TensorFrame([p1]), p1, 9, 4)
            t1 = _tokens(_drain(eng))
            eng.submit(TensorFrame([p2]), p2, 9, 4)
            t2 = _tokens(_drain(eng))
            np.testing.assert_array_equal(t1, sim_oracle(model.vocab, p1, 9))
            np.testing.assert_array_equal(t2, sim_oracle(model.vocab, p2, 9))
            snap = eng.snapshot()
            assert snap["prefix_misses"] == 1    # p1: eligible, cold
            assert snap["prefix_hits"] == 1      # p2: both chunks warm
            assert snap["prefix_hit_tokens"] == 16
            assert snap["prefix_publishes"] == 2
            assert snap["prefix_entries"] == 2
            assert snap["prefix_refs"] == 0      # released at slot free
        finally:
            eng.stop()

    def test_partial_prefix_hit_publishes_the_divergent_chunk(self):
        pool = PrefixCache(grain=8)
        eng, model = _sim_engine(pool)
        try:
            p1 = np.arange(17, dtype=np.int32)[None]
            p2 = p1.copy()
            p2[0, 12] = 55  # diverges inside chunk 1
            eng.submit(TensorFrame([p1]), p1, 6, 4)
            _drain(eng)
            eng.submit(TensorFrame([p2]), p2, 6, 4)
            t2 = _tokens(_drain(eng))
            np.testing.assert_array_equal(t2, sim_oracle(model.vocab, p2, 6))
            snap = eng.snapshot()
            assert snap["prefix_hits"] == 1
            assert snap["prefix_hit_tokens"] == 8   # chunk 0 only
            assert snap["prefix_publishes"] == 3    # p2's chunk 1 is new
        finally:
            eng.stop()

    def test_short_prompt_neither_hit_nor_miss(self):
        """A prompt without one FULL grain chunk beyond the final token
        is ineligible — it must not pollute the hit-rate denominator."""
        pool = PrefixCache(grain=8)
        eng, _ = _sim_engine(pool)
        try:
            p = np.arange(8, dtype=np.int32)[None]  # (8-1)//8 == 0 chunks
            eng.submit(TensorFrame([p]), p, 4, 4)
            _drain(eng)
            snap = eng.snapshot()
            assert snap["prefix_hits"] == 0 and snap["prefix_misses"] == 0
            assert snap["prefix_publishes"] == 0
        finally:
            eng.stop()

    def test_pins_span_slot_occupancy_trim_cannot_reclaim(self):
        pool = PrefixCache(grain=8)
        eng, model = _sim_engine(pool, step_ms=30.0)
        try:
            p1 = np.arange(17, dtype=np.int32)[None]
            eng.submit(TensorFrame([p1]), p1, 3, 4)
            _drain(eng)  # publish both chunks, fast enough at 3 tokens
            p2 = p1.copy()
            p2[0, 16] = 55
            eng.submit(TensorFrame([p2]), p2, 64, 4)
            deadline = time.monotonic() + 20
            while pool.snapshot()["prefix_refs"] == 0:
                assert time.monotonic() < deadline, "attach never pinned"
                time.sleep(0.005)
            # live reader holds both entries: trim reclaims NOTHING
            assert pool.trim() == 0
            assert pool.snapshot()["prefix_entries"] == 2
        finally:
            eng.stop()
        # stop() released the mid-stream reader's pins
        assert pool.snapshot()["prefix_refs"] == 0

    def test_resume_attaches_and_stays_bit_exact(self):
        """A resumed stream shares the attach path (prefill_src starts
        with the same prompt bytes): warm resume AND cache-cold resume
        both reproduce the oracle suffix exactly."""
        pool = PrefixCache(grain=8)
        eng, model = _sim_engine(pool, resume_sig="SIG")
        p = np.arange(17, dtype=np.int32)[None]
        try:
            eng.submit(TensorFrame([p]), p, 12, 4)
            oracle = _tokens(_drain(eng))
        finally:
            eng.stop()
        for pool2 in (pool, PrefixCache(grain=8)):  # warm, then cold
            e2, _ = _sim_engine(pool2, resume_sig="SIG")
            try:
                e2.submit(TensorFrame([p]), p, 12, 4,
                          resume={"tokens_done": 5,
                                  "prefix": oracle[:, :5]})
                got = _tokens(_drain(e2))
            finally:
                e2.stop()
            np.testing.assert_array_equal(got, oracle[:, 5:])


# ---------------------------------------------------------------------------
# Real model: warm hits bit-identical to cold paths
# ---------------------------------------------------------------------------
def _zoo_engine(pool, extra=None):
    from nnstreamer_tpu.models.transformer import build_slot_stream

    props = {k: str(v) for k, v in PROPS.items()}
    if extra:
        props.update(extra)
    model, params, max_seq = build_slot_stream(props, 2)
    eng = SlotEngine(model, params, max_seq=max_seq, chunk=4,
                     prefill_chunk=4, prefix_cache=pool, resume_sig="Z")
    eng.start()
    return eng


class TestZooBitExactness:
    @pytest.mark.parametrize("extra", [
        # tier-1 budget: ~22s; greedy warm-hit bit-exactness stays tier-1
        # via the fused/unfused element-wiring pins below, so tier-1 keeps
        # only the harder seeded-topk variant at engine level
        pytest.param(None, marks=pytest.mark.slow),
        SAMPLING,
    ], ids=["greedy", "seeded-topk"])
    def test_warm_hit_bit_identical_to_oneshot(self, rng, extra):
        """The core contract: a warm-hit stream's tokens are bit-equal
        to the seed one-shot path — the attach restored the byte-exact
        state of a cold chunked prefill paused at the boundary."""
        p1 = rng.integers(0, 61, (1, 19)).astype(np.int32)
        p2 = p1.copy()
        p2[0, 17:] = (p2[0, 17:] + 9) % 61  # shared 16-token prefix
        n = 8
        pool = PrefixCache(grain=8)
        eng = _zoo_engine(pool, extra)
        try:
            eng.submit(TensorFrame([p1]), p1, n, 4)
            t1 = _tokens(_drain(eng))
            eng.submit(TensorFrame([p2]), p2, n, 4)
            t2 = _tokens(_drain(eng))
            snap = eng.snapshot()
        finally:
            eng.stop()
        np.testing.assert_array_equal(t1, _oneshot(p1, n, extra))
        np.testing.assert_array_equal(t2, _oneshot(p2, n, extra))
        assert snap["prefix_hits"] == 1
        assert snap["prefix_hit_tokens"] == 16

    def test_attach_touches_only_its_slot(self, rng):
        """Attaching cached pages into a joining slot leaves every
        NEIGHBOR page bit-untouched (the page-reuse contract extends
        to the shared pool)."""
        import jax

        from nnstreamer_tpu.models.transformer import build_slot_stream

        props = {k: str(v) for k, v in PROPS.items()}
        model, params, _ = build_slot_stream(props, 3)
        cache = model.reset_slot(model.init_cache(), np.int32(0))
        p0 = rng.integers(0, 61, (1, 9)).astype(np.int32)
        cache, _ = model.prefill_fn(9)(params, cache, p0, np.int32(0))
        pages = model.export_prefix(cache, 0, 0, 8)
        before = [np.array(leaf)[:2] for leaf in jax.tree.leaves(cache)]
        cache = model.reset_slot(cache, np.int32(2))
        cache = model.attach_prefix(cache, 2, [pages], 8)
        after = [np.array(leaf)[:2] for leaf in jax.tree.leaves(cache)]
        for b, a in zip(before, after):
            np.testing.assert_array_equal(b, a)

    def test_cache_cold_resume_bit_exact(self, rng):
        """Migrated stream resumed on a cache-cold server: the fresh
        pool has nothing to attach beyond what the resume re-prefills,
        and the suffix stays bit-identical."""
        p = rng.integers(0, 61, (1, 18)).astype(np.int32)
        n = 10
        eng = _zoo_engine(PrefixCache(grain=8))
        try:
            eng.submit(TensorFrame([p]), p, n, 4)
            oracle = _tokens(_drain(eng))
        finally:
            eng.stop()
        e2 = _zoo_engine(PrefixCache(grain=8))  # cold pool
        try:
            e2.submit(TensorFrame([p]), p, n, 4,
                      resume={"tokens_done": 4, "prefix": oracle[:, :4]})
            got = _tokens(_drain(e2))
        finally:
            e2.stop()
        np.testing.assert_array_equal(got, oracle[:, 4:])


# ---------------------------------------------------------------------------
# Element + pipeline wiring
# ---------------------------------------------------------------------------
def _prefix_pipeline(extra_props="", fuse=True, slots=1):
    return parse_pipeline(
        f"appsrc name=src ! tensor_generator name=gen slots={slots} "
        f"custom={CUSTOM} max-new=8 chunk=4 prefill-chunk=4 "
        f"{extra_props} ! tensor_sink name=out", fuse=fuse)


class TestElementWiring:
    @pytest.mark.parametrize("fuse", [True, False],
                             ids=["fused", "unfused"])
    def test_pipeline_warm_hit_bit_exact_and_accounted(self, rng, fuse):
        p1 = rng.integers(0, 61, (1, 19)).astype(np.int32)
        p2 = p1.copy()
        p2[0, 18] = (p2[0, 18] + 1) % 61
        pipe = _prefix_pipeline("prefix-cache=on prefix-grain=8",
                                fuse=fuse)
        pipe.start()
        for p in (p1, p2):  # slots=1 serializes: p1 publishes, p2 hits
            pipe["src"].push(p)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=180)
        frames = pipe["out"].frames
        health = pipe.health()["gen"]
        pipe.stop()
        by_seq = {}
        for f in frames:
            by_seq.setdefault(f.meta["stream_seq"], []).append(f)
        got = [_tokens(fs) for fs in by_seq.values()]
        for p in (p1, p2):
            w = _oneshot(p, 8)
            assert any(np.array_equal(g, w) for g in got)
        assert health["prefix_hits"] == 1
        assert health["prefix_misses"] == 1
        assert health["prefix_hit_tokens"] == 16

    def test_cache_off_is_zero_change(self, rng):
        """Armed-off default: no prefix_* health keys, identical token
        stream — the cache cannot change behavior until switched on."""
        p = rng.integers(0, 61, (1, 19)).astype(np.int32)
        pipe = _prefix_pipeline()
        pipe.start()
        pipe["src"].push(p)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        frames = pipe["out"].frames
        health = pipe.health()["gen"]
        pipe.stop()
        assert not any(k.startswith("prefix_") for k in health)
        np.testing.assert_array_equal(_tokens(frames), _oneshot(p, 8))

    def test_prefix_cache_needs_slots(self):
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator name=gen custom={CUSTOM} "
            "max-new=4 prefix-cache=on ! tensor_sink name=out")
        with pytest.raises(Exception, match="slots >= 1"):
            pipe.start()
        pipe.stop()

    def test_grain_rounds_up_to_prefill_chunk(self):
        pipe = _prefix_pipeline("prefix-cache=on prefix-grain=6")
        pipe.start()
        try:
            assert pipe["gen"]._prefix_pool.grain == 8  # 6 -> ceil to 8
        finally:
            pipe["src"].end_of_stream()
            pipe.wait(timeout=60)
            pipe.stop()

    def test_memory_pressure_trims_cold_prefixes_first(self, rng):
        """The PR-14 trim ladder reclaims refs==0 prefix entries on the
        high-watermark crossing — and the prefix hook runs FIRST."""
        p = rng.integers(0, 61, (1, 19)).astype(np.int32)
        pipe = _prefix_pipeline("prefix-cache=on prefix-grain=8")
        pipe.start()
        clk = {"t": 0.0}
        mem = {"frac": 0.0}
        mon = pipe.enable_memory_monitor(
            high=0.9, low=0.7, min_poll_s=0.0,
            sample=lambda: (int(mem["frac"] * 1000), 1000, 0),
            clock=lambda: clk["t"])
        pipe["src"].push(p)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        pool = pipe["gen"]._prefix_pool
        assert pool.snapshot()["prefix_entries"] == 2
        mem["frac"] = 0.95
        clk["t"] = 1.0
        assert mon.poll() is True
        assert pool.snapshot()["prefix_entries"] == 0
        assert pool.snapshot()["prefix_evictions"] == 2
        assert mon.trimmed_entries >= 2
        pipe.stop()

    def test_restart_is_cache_cold(self, rng):
        """stop() drops the pool: supervision restart = deliberately
        cache-cold (the chaos failover contract relies on it)."""
        pipe = _prefix_pipeline("prefix-cache=on prefix-grain=8")
        pipe.start()
        pool1 = pipe["gen"]._prefix_pool
        assert pool1 is not None
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        pipe.stop()
        assert pipe["gen"]._prefix_pool is None


# ---------------------------------------------------------------------------
# The chaos acceptance (tier-1, chaos-marked)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_prefix_cache_chaos_smoke():
    """The fleet acceptance contract: N clients sharing one prompt
    prefix are routed by ``affinity-key=prefix`` to the one warm owner;
    a mid-decode rolling restart of that owner forces bit-exact
    cache-cold failover (zero lost/duplicated tokens), the restarted
    owner comes back deliberately cold and re-warms, the fleet hit rate
    clears its floor, and the observatory's fleet prefix hit/miss
    rollup is integer-exact against the summed per-server ledgers,
    retired rows included."""
    from tools.chaos_fleet import run_prefix_script

    v = run_prefix_script(servers=3, clients=6, seed=0)
    assert v["ok"], v
    # the contract, spelled out
    assert v["mismatched"] == 0 and v["exact"] == v["streams"]
    assert v["warm_wave"]["prefix_misses"] == 1
    assert v["warm_wave"]["prefix_hits"] == v["clients"] - 1
    assert v["warm_wave"]["prefix_hit_tokens"] == (v["clients"] - 1) * 64
    assert v["hit_ratio"] >= 0.5
    assert v["migrations"] >= 1 and v["resume_failures"] == 0
    cc = v["crosscheck"]
    assert cc["exact"]
    assert cc["rollup_prefix_hits"] == cc["ledger_prefix_hits"]
    assert cc["rollup_prefix_misses"] == cc["ledger_prefix_misses"]
    assert v["rolling_restart"]["drain_dropped"] == 0
    assert v["breaker_trips"] == 0
