""".jaxexport / .stablehlo model files: the TPU-native interchange format.

Any jitted JAX function serialized with ``jax.export`` runs as a
tensor_filter model file — the XLA answer to the reference's drop-a-file
subplugin flow (``tensor_filter_tensorflow_lite.cc:158`` embeds a vendor
interpreter; here the artifact IS compiler IR).  Covers batch-polymorphic
(symbolic leading dim) and fixed-shape artifacts.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.jax_xla import JaxXla, export_model
from nnstreamer_tpu.elements.filter import SingleShot, detect_framework
from nnstreamer_tpu.pipeline import parse_pipeline


def _affine(params, xs):
    return [xs[0] * params["w"] + params["b"]]


@pytest.fixture(scope="module")
def poly_model(tmp_path_factory):
    path = tmp_path_factory.mktemp("jx") / "affine.jaxexport"
    export_model(_affine, {"w": np.float32(2.0), "b": np.float32(1.0)},
                 [((4,), np.float32)], str(path))
    return str(path)


@pytest.fixture(scope="module")
def fixed_model(tmp_path_factory):
    path = tmp_path_factory.mktemp("jx") / "affine_fixed.stablehlo"
    export_model(_affine, {"w": np.float32(3.0), "b": np.float32(0.0)},
                 [((4,), np.float32)], str(path), batch_polymorphic=False)
    return str(path)


class TestJaxExportModels:
    def test_framework_auto(self, poly_model, fixed_model):
        assert detect_framework(poly_model) == "jax-xla"
        assert detect_framework(fixed_model) == "jax-xla"

    def test_pipeline_end_to_end(self, poly_model):
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=auto "
            f"model={poly_model} ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(5):
            pipe["src"].push(np.full((4,), float(i), np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        vals = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
        pipe.stop()
        assert len(vals) == 5
        for i, v in enumerate(vals):
            np.testing.assert_allclose(v, np.full((4,), i * 2.0 + 1.0))

    def test_batch_polymorphic_native_microbatch(self, poly_model):
        be = JaxXla()
        be.open(poly_model, {})
        try:
            xs = np.arange(12, dtype=np.float32).reshape(3, 4)
            (out,) = be.invoke_batch([xs])
            np.testing.assert_allclose(np.asarray(out), xs * 2.0 + 1.0)
            # per-frame invoke strips the symbolic batch dim
            (o1,) = be.invoke([np.ones(4, np.float32)])
            np.testing.assert_allclose(np.asarray(o1), np.full(4, 3.0))
        finally:
            be.close()

    def test_fixed_shape_invoke_and_unrolled_batch(self, fixed_model):
        with SingleShot("jax-xla", fixed_model) as m:
            (o,) = m.invoke([np.ones(4, np.float32)])
            np.testing.assert_allclose(np.asarray(o), np.full(4, 3.0))
            xs = np.arange(8, dtype=np.float32).reshape(2, 4)
            (ob,) = m.invoke_batch([xs])
            np.testing.assert_allclose(np.asarray(ob), xs * 3.0)

    def test_model_info_fixed(self, fixed_model):
        be = JaxXla()
        be.open(fixed_model, {})
        try:
            in_spec, out_spec = be.get_model_info()
            assert in_spec.tensors[0].shape == (4,)
            assert out_spec.tensors[0].shape == (4,)
        finally:
            be.close()

    def test_model_info_symbolic_derives_from_stream(self, poly_model):
        from nnstreamer_tpu.core.types import StreamSpec

        be = JaxXla()
        be.open(poly_model, {})
        try:
            in_spec, out_spec = be.get_model_info()
            assert in_spec is None and out_spec is None
            got = be.set_input_info(
                StreamSpec.from_string(
                    "other/tensors,num_tensors=1,dimensions=4,types=float32"))
            assert got.tensors[0].shape == (4,)
        finally:
            be.close()

    def test_garbage_artifact_clear_error(self, tmp_path):
        bad = tmp_path / "junk.stablehlo"
        bad.write_bytes(b"module @not_a_flatbuffer {}")
        be = JaxXla()
        with pytest.raises(ValueError, match="jax.export artifact"):
            be.open(str(bad), {})

    def test_missing_file_clear_error(self):
        be = JaxXla()
        with pytest.raises(FileNotFoundError, match="exported-model"):
            be.open("/nonexistent/model.jaxexport", {})
