"""Unit tests for the core type system.

Modeled on the reference's common unit tests
(``tests/common/unittest_common.cc``): type<->string round trips, dim string
parse/print, size calculation, info equality/compat, caps intersection,
flexible header round trip, sparse encode/decode — positive and negative
("_n") cases.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import types as T


class TestDtypes:
    def test_roundtrip_all_names(self):
        for name in T.all_type_names():
            dt = T.dtype_from_name(name)
            assert T.dtype_to_name(dt) == name

    def test_case_insensitive(self):
        assert T.dtype_from_name(" FLOAT32 ") == np.dtype(np.float32)

    def test_unknown_name_n(self):
        with pytest.raises(ValueError):
            T.dtype_from_name("float128")

    def test_bfloat16_present(self):
        # TPU-native extension beyond the reference's 11 types
        assert "bfloat16" in T.all_type_names()


class TestDims:
    def test_parse_reference_dialect(self):
        # "3:224:224:1" is C:W:H:N innermost-first -> numpy (1,224,224,3)
        assert T.parse_dims_string("3:224:224:1") == (1, 224, 224, 3)

    def test_roundtrip(self):
        s = "3:224:224:1"
        assert T.dims_to_string(T.parse_dims_string(s)) == s

    def test_flexible_dim(self):
        assert T.parse_dims_string("3:0:0:1") == (1, None, None, 3)

    def test_rank_limit_n(self):
        with pytest.raises(ValueError):
            T.parse_dims_string(":".join(["2"] * 17))

    def test_empty_n(self):
        with pytest.raises(ValueError):
            T.parse_dims_string("")


class TestTensorSpec:
    def test_size(self):
        # reference gst_tensor_info_get_size semantics
        s = T.TensorSpec((1, 224, 224, 3), np.uint8)
        assert s.num_elements == 224 * 224 * 3
        assert s.nbytes == 224 * 224 * 3

    def test_flexible_size_none(self):
        s = T.TensorSpec((None, 224, 224, 3), np.uint8)
        assert s.nbytes is None and not s.is_static

    def test_string_roundtrip(self):
        s = T.TensorSpec.from_string("float32:10:1:1:1")
        assert s.dtype == np.dtype(np.float32)
        assert s.shape == (1, 1, 1, 10)
        assert s.to_string() == "float32:10:1:1:1"

    def test_compat_wildcard(self):
        a = T.TensorSpec((None, 224, 224, 3), np.uint8)
        b = T.TensorSpec((8, 224, 224, 3), np.uint8)
        assert a.is_compatible(b)
        assert a.intersect(b).shape == (8, 224, 224, 3)

    def test_incompatible_dtype_n(self):
        a = T.TensorSpec((1, 2), np.uint8)
        b = T.TensorSpec((1, 2), np.int8)
        assert not a.is_compatible(b)
        assert a.intersect(b) is None

    def test_matches_array(self):
        s = T.TensorSpec((None, 3), np.float32)
        assert s.matches(np.zeros((5, 3), np.float32))
        assert not s.matches(np.zeros((5, 4), np.float32))

    def test_bad_dim_n(self):
        with pytest.raises(ValueError):
            T.TensorSpec((0, 3), np.float32)


class TestStreamSpec:
    def make(self):
        return T.StreamSpec(
            (
                T.TensorSpec((1, 224, 224, 3), np.uint8),
                T.TensorSpec((1, 1001), np.float32),
            ),
            T.FORMAT_STATIC,
        )

    def test_validate(self):
        assert self.make().validate()
        assert not T.StreamSpec((), T.FORMAT_STATIC).validate()

    def test_string_roundtrip(self):
        s = self.make()
        s2 = T.StreamSpec.from_string(s.to_string())
        assert s2 == s

    def test_parse_caps_like(self):
        s = T.StreamSpec.from_string(
            "tensors,format=static,num=1,dimensions=3:224:224:1,types=uint8,framerate=30/1"
        )
        assert s.num_tensors == 1
        assert s.tensors[0].shape == (1, 224, 224, 3)
        assert s.framerate == 30

    def test_intersect(self):
        a = T.StreamSpec((T.TensorSpec((None, 10), np.float32),))
        b = T.StreamSpec((T.TensorSpec((4, 10), np.float32),))
        m = a.intersect(b)
        assert m.tensors[0].shape == (4, 10)

    def test_format_mismatch_n(self):
        a = self.make()
        b = T.StreamSpec(a.tensors, T.FORMAT_FLEXIBLE)
        assert not a.is_compatible(b)

    def test_any_wildcard(self):
        # ANY (zero-tensor flexible) matches and intersects with anything
        s = self.make()
        assert T.ANY.is_compatible(s) and s.is_compatible(T.ANY)
        assert T.ANY.intersect(s) == s
        assert s.intersect(T.ANY) == s

    def test_numpy_int_dims_accepted(self):
        s = T.TensorSpec((np.int64(2), np.int32(3)), np.uint8)
        assert s.shape == (2, 3) and all(type(d) is int for d in s.shape)

    def test_bool_dim_rejected_n(self):
        with pytest.raises(ValueError):
            T.TensorSpec((True, 3), np.uint8)

    def test_pick_combination(self):
        # input-combination subset/reorder (reference tensor_filter.c:723)
        s = self.make()
        p = s.pick([1, 0])
        assert p.tensors[0].dtype == np.dtype(np.float32)
        assert p.tensors[1].dtype == np.dtype(np.uint8)


class TestFlexHeader:
    def test_roundtrip(self):
        spec = T.TensorSpec((2, 3, 4), np.float16)
        blob = T.pack_flex_header(spec) + b"payload"
        parsed, off = T.unpack_flex_header(blob)
        assert parsed.shape == (2, 3, 4)
        assert parsed.dtype == np.dtype(np.float16)
        assert blob[off:] == b"payload"

    def test_bad_magic_n(self):
        with pytest.raises(ValueError):
            T.unpack_flex_header(b"\x00" * 32)

    def test_flexible_spec_rejected_n(self):
        with pytest.raises(ValueError):
            T.pack_flex_header(T.TensorSpec((None, 3), np.uint8))


class TestSparse:
    def test_roundtrip(self, rng):
        dense = rng.random((8, 16)).astype(np.float32)
        dense[dense < 0.8] = 0.0
        vals, idx, spec = T.sparse_encode(dense)
        assert len(vals) == np.count_nonzero(dense)
        out = T.sparse_decode(vals, idx, spec)
        np.testing.assert_array_equal(out, dense)

    def test_all_zero(self):
        dense = np.zeros((4, 4), np.int8)
        vals, idx, spec = T.sparse_encode(dense)
        assert len(vals) == 0
        np.testing.assert_array_equal(T.sparse_decode(vals, idx, spec), dense)
