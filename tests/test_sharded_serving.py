"""Sharded serving: one logical tensor_filter spread across a device mesh
via ``mesh_*`` custom props (params sharded by parallel/sharding.py rules,
micro-batches scattered over dp, XLA SPMD collectives).

Reference analog: none — the reference fans *streams* out over
nnstreamer-edge (SURVEY §2.3); intra-model sharding of serving is
TPU-native net-new.  Runs on the conftest 8-device CPU mesh.
"""

import jax
import numpy as np

from nnstreamer_tpu.backends.base import find_backend
from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.pipeline import parse_pipeline

TRANSFORMER = "arch:transformer,dtype:float32,vocab:64,d_model:32,heads:2,layers:2,d_ff:64,seq:16,seed:7"


def _tokens(rng, n, t=16):
    return rng.integers(0, 64, (n, t)).astype(np.int32)


def test_sharded_matches_unsharded(rng):
    toks = _tokens(rng, 8)
    with SingleShot(
        framework="jax-xla", model="zoo", custom=TRANSFORMER
    ) as plain:
        want = np.asarray(plain.invoke_batch([toks])[0])
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:2,mesh_tp:2",
    ) as sharded:
        be = sharded.backend
        assert be._mesh is not None and be._mesh.shape["dp"] == 2
        # params actually landed sharded: at least one leaf spans >1 device
        spans = [
            len(leaf.sharding.device_set)
            for leaf in jax.tree.leaves(be._params)
        ]
        assert max(spans) > 1, "no parameter is sharded across devices"
        got = np.asarray(sharded.invoke_batch([toks])[0])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sharded_odd_batch_bucketing(rng):
    """Batch not divisible by dp: bucket pads to an even scatter and
    slices back."""
    toks = _tokens(rng, 5)
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:4",
    ) as s:
        out = np.asarray(s.invoke_batch([toks])[0])
    assert out.shape[0] == 5


def test_sharded_single_invoke_replicates(rng):
    toks = _tokens(rng, 1)[0]
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:2,mesh_tp:2",
    ) as s:
        out = np.asarray(s.invoke([toks])[0])
    assert out.shape == (16, 64)


def test_sharded_pipeline_end_to_end(rng):
    """Full streaming pipeline over a sharded filter: appsrc -> filter
    (mesh dp×tp, micro-batched) -> sink; outputs match the unsharded
    pipeline frame-for-frame."""
    frames = [_tokens(rng, 1)[0] for _ in range(8)]

    def run(custom):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            f"tensor_filter framework=jax-xla model=zoo custom={custom} "
            "max-batch=4 batch-timeout=50 ! "
            "tensor_sink name=out",
            name="sharded-serve",
        )
        pipe.start()
        for f in frames:
            pipe["src"].push(f)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
        pipe.stop()
        return outs

    plain = run(TRANSFORMER)
    sharded = run(TRANSFORMER + ",mesh_dp:2,mesh_tp:2")
    assert len(plain) == len(sharded) == 8
    for a, b in zip(plain, sharded):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-4)


def test_sharded_generation_matches_unsharded(rng):
    """mesh_* props compose with generate:<N>: the KV-cache decode loop
    runs under GSPMD with tp-sharded params; tokens must be identical."""
    toks = _tokens(rng, 4, t=8)
    with SingleShot(
        framework="jax-xla", model="zoo", custom=TRANSFORMER + ",generate:3"
    ) as plain:
        want = np.asarray(plain.invoke_batch([toks])[0])
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",generate:3,mesh_dp:2,mesh_tp:2",
    ) as sharded:
        got = np.asarray(sharded.invoke_batch([toks])[0])
    assert want.shape == (4, 11)
    np.testing.assert_array_equal(got, want)


def _setup_module_guard():
    # fail fast if the zoo alias used above ever changes
    assert find_backend("jax-xla") is not None


_setup_module_guard()
