"""Sharded serving: one logical tensor_filter spread across a device mesh
via the first-class ``mesh=`` prop (legacy ``mesh_*`` custom props still
accepted): params sharded by parallel/sharding.py rules and staged across
the whole mesh, ``invoke``/``invoke_batch`` compiled under NamedSharding
in/out specs, micro-batches scattered over dp, XLA SPMD collectives.

Reference analog: none — the reference fans *streams* out over
nnstreamer-edge (SURVEY §2.3); intra-model sharding of serving is
TPU-native net-new.  Runs on the conftest 8-device CPU mesh.
"""

import time

import jax
import numpy as np
import pytest

from nnstreamer_tpu.backends.base import find_backend
from nnstreamer_tpu.backends.jax_xla import (
    register_jax_model,
    unregister_jax_model,
)
from nnstreamer_tpu.core.buffer import DeviceBufferPool
from nnstreamer_tpu.core.resilience import FAULTS
from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.parallel.mesh import mesh_spec_str, parse_mesh_spec
from nnstreamer_tpu.pipeline import parse_pipeline

TRANSFORMER = "arch:transformer,dtype:float32,vocab:64,d_model:32,heads:2,layers:2,d_ff:64,seq:16,seed:7"


def _tokens(rng, n, t=16):
    return rng.integers(0, 64, (n, t)).astype(np.int32)


def test_sharded_matches_unsharded(rng):
    toks = _tokens(rng, 8)
    with SingleShot(
        framework="jax-xla", model="zoo", custom=TRANSFORMER
    ) as plain:
        want = np.asarray(plain.invoke_batch([toks])[0])
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:2,mesh_tp:2",
    ) as sharded:
        be = sharded.backend
        assert be._mesh is not None and be._mesh.shape["dp"] == 2
        # params actually landed sharded: at least one leaf spans >1 device
        spans = [
            len(leaf.sharding.device_set)
            for leaf in jax.tree.leaves(be._params)
        ]
        assert max(spans) > 1, "no parameter is sharded across devices"
        got = np.asarray(sharded.invoke_batch([toks])[0])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sharded_odd_batch_bucketing(rng):
    """Batch not divisible by dp: bucket pads to an even scatter and
    slices back."""
    toks = _tokens(rng, 5)
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:4",
    ) as s:
        out = np.asarray(s.invoke_batch([toks])[0])
    assert out.shape[0] == 5


def test_sharded_single_invoke_replicates(rng):
    toks = _tokens(rng, 1)[0]
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",mesh_dp:2,mesh_tp:2",
    ) as s:
        out = np.asarray(s.invoke([toks])[0])
    assert out.shape == (16, 64)


def test_sharded_pipeline_end_to_end(rng):
    """Full streaming pipeline over a sharded filter: appsrc -> filter
    (mesh dp×tp, micro-batched) -> sink; outputs match the unsharded
    pipeline frame-for-frame."""
    frames = [_tokens(rng, 1)[0] for _ in range(8)]

    def run(custom):
        pipe = parse_pipeline(
            "appsrc name=src ! "
            f"tensor_filter framework=jax-xla model=zoo custom={custom} "
            "max-batch=4 batch-timeout=50 ! "
            "tensor_sink name=out",
            name="sharded-serve",
        )
        pipe.start()
        for f in frames:
            pipe["src"].push(f)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
        pipe.stop()
        return outs

    plain = run(TRANSFORMER)
    sharded = run(TRANSFORMER + ",mesh_dp:2,mesh_tp:2")
    assert len(plain) == len(sharded) == 8
    for a, b in zip(plain, sharded):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-4)


def test_sharded_generation_matches_unsharded(rng):
    """mesh_* props compose with generate:<N>: the KV-cache decode loop
    runs under GSPMD with tp-sharded params; tokens must be identical."""
    toks = _tokens(rng, 4, t=8)
    with SingleShot(
        framework="jax-xla", model="zoo", custom=TRANSFORMER + ",generate:3"
    ) as plain:
        want = np.asarray(plain.invoke_batch([toks])[0])
    with SingleShot(
        framework="jax-xla",
        model="zoo",
        custom=TRANSFORMER + ",generate:3,mesh_dp:2,mesh_tp:2",
    ) as sharded:
        got = np.asarray(sharded.invoke_batch([toks])[0])
    assert want.shape == (4, 11)
    np.testing.assert_array_equal(got, want)


def _setup_module_guard():
    # fail fast if the zoo alias used above ever changes
    assert find_backend("jax-xla") is not None


_setup_module_guard()


# ---------------------------------------------------------------------------
# mesh= config grammar (parallel/mesh.py — the ONE grammar every surface
# shares: filter/generator props, jax-xla backend, bench BENCH_MESH)
# ---------------------------------------------------------------------------
class TestMeshSpecGrammar:
    def test_parse_valid(self):
        assert parse_mesh_spec("tp:4") == {"tp": 4}
        assert parse_mesh_spec("dp:2,tp:2") == {"dp": 2, "tp": 2}
        assert parse_mesh_spec(" DP:2 , tp:-1 ") == {"dp": 2, "tp": -1}
        for empty in ("", "0", "off", "none"):
            assert parse_mesh_spec(empty) == {}

    @pytest.mark.parametrize("bad", [
        "xp:2",          # unknown axis
        "tp",            # no size
        "tp:two",        # non-integer
        "tp:0",          # zero
        "tp:-2",         # below -1
        "tp:2,tp:4",     # duplicate
        "dp:-1,tp:-1",   # two wildcards
    ])
    def test_parse_invalid_is_loud(self, bad):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad)

    def test_canonical_string(self):
        assert mesh_spec_str({}) == "0"
        assert mesh_spec_str({"tp": 2, "dp": 4}) == "dp:4,tp:2"

    def test_filter_refuses_bad_spec_at_start(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=passthrough "
            "mesh=xp:2 ! tensor_sink name=out")
        with pytest.raises(Exception, match="unknown axis"):
            pipe.start()
        pipe.stop()

    def test_filter_refuses_meshless_backend(self):
        """A backend that would silently ignore mesh= is refused loudly
        (passthrough has no mesh support)."""
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter framework=passthrough "
            "mesh=tp:2 ! tensor_sink name=out")
        with pytest.raises(Exception, match="does not support mesh"):
            pipe.start()
        pipe.stop()


# ---------------------------------------------------------------------------
# 1-device-mesh bit parity: the full sharded machinery (NamedSharding
# in/out compile, scatter path, replicate-on-invoke) with zero parallelism
# to hide behind — outputs must be BIT-identical to the unsharded backend
# ---------------------------------------------------------------------------
class TestOneDeviceMeshBitParity:
    def test_invoke_and_batch_bit_identical(self, rng):
        toks_b = _tokens(rng, 4)
        toks_1 = _tokens(rng, 1)[0]
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER) as plain:
            want_b = np.asarray(plain.invoke_batch([toks_b])[0])
            want_1 = np.asarray(plain.invoke([toks_1])[0])
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER, mesh="dp:1") as sharded:
            assert sharded.backend._mesh is not None
            got_b = np.asarray(sharded.invoke_batch([toks_b])[0])
            got_1 = np.asarray(sharded.invoke([toks_1])[0])
        np.testing.assert_array_equal(got_b, want_b)
        np.testing.assert_array_equal(got_1, want_1)

    def test_generation_bit_identical(self, rng):
        toks = _tokens(rng, 2, t=8)
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER + ",generate:3") as plain:
            want = np.asarray(plain.invoke_batch([toks])[0])
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER + ",generate:3",
                        mesh="tp:1") as sharded:
            got = np.asarray(sharded.invoke_batch([toks])[0])
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("fuse", [True, False])
    def test_pipeline_bit_identical_fused_and_unfused(self, rng, fuse):
        """Streaming parity in BOTH dataplanes: micro-batched serving
        over a 1-device mesh is bit-identical to unsharded, and the
        sharded outputs really ride the async dispatch window."""
        frames = [_tokens(rng, 1)[0] for _ in range(6)]

        def run(mesh_tok):
            pipe = parse_pipeline(
                "appsrc name=src ! "
                f"tensor_filter name=f framework=jax-xla model=zoo "
                f"custom={TRANSFORMER} {mesh_tok}"
                "max-batch=3 batch-timeout=50 ! tensor_sink name=out",
                name="mesh1p",
                fuse=fuse,
            )
            pipe.start()
            for f in frames:
                pipe["src"].push(f)
            pipe["src"].end_of_stream()
            pipe.wait(timeout=120)
            outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
            win_async = pipe["f"]._win_async
            health = pipe.health()["f"]
            pipe.stop()
            return outs, win_async, health

        plain, _, _ = run("")
        sharded, win_async, health = run("mesh=dp:1 ")
        assert len(plain) == len(sharded) == 6
        for a, b in zip(plain, sharded):
            np.testing.assert_array_equal(b, a)
        # sharded jax outputs keep the async-window capability
        assert win_async is True
        # mesh facts are in health() (exported as nns.mesh.* by the
        # telemetry collector)
        assert health["mesh_devices"] == 1
        assert health["mesh_dp"] == 1 and health["mesh_axes"] == "dp:1"


# ---------------------------------------------------------------------------
# tensor_query e2e (acceptance): a tp-/dp-sharded model serves through
# BOTH transports; tokens bit-identical to the unsharded server
# ---------------------------------------------------------------------------
class TestShardedQueryServing:
    @pytest.mark.parametrize("transport", ["tcp", "grpc"])
    def test_sharded_generation_served_bit_identical(self, rng, transport):
        gen = TRANSFORMER + ",generate:3"
        prompts = [_tokens(rng, 1, t=8)[0] for _ in range(4)]

        def serve(mesh_tok, sid):
            server = parse_pipeline(
                f"tensor_query_serversrc name=ssrc id={sid} port=0 "
                f"connect-type={transport} ! "
                f"tensor_filter framework=jax-xla model=zoo "
                f"custom={gen} {mesh_tok}max-batch=2 batch-timeout=30 ! "
                f"tensor_query_serversink id={sid}",
                name=f"shq{sid}",
            )
            server.start()
            port = server["ssrc"].props["port"]
            client = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                f"connect-type={transport} ! tensor_sink name=out",
                name=f"shqc{sid}",
            )
            client.start()
            try:
                for p in prompts:
                    client["src"].push(p)
                client["src"].end_of_stream()
                client.wait(timeout=120)
                outs = [np.asarray(f.tensors[0])
                        for f in client["out"].frames]
                mesh_health = {
                    k: v for k, v in server.health().get(
                        "tensor_filter0", server.health().get("f", {})
                    ).items() if k.startswith("mesh_")
                } if mesh_tok else {}
            finally:
                client.stop()
                server.stop()
            return outs, mesh_health

        plain, _ = serve("", 571 if transport == "tcp" else 573)
        sharded, _ = serve(
            "mesh=dp:2,tp:2 ", 572 if transport == "tcp" else 574)
        assert len(plain) == len(sharded) == 4
        for a, b in zip(plain, sharded):
            # greedy token generation: the served completions must be
            # the SAME tokens (proven stable on this mesh/model size by
            # test_sharded_generation_matches_unsharded)
            np.testing.assert_array_equal(b, a)


# ---------------------------------------------------------------------------
# Atomic sharded hot swap: staging covers the WHOLE mesh before the
# pointer exchange; every failure mode keeps the old mesh serving
# ---------------------------------------------------------------------------
#: two versions of a tiny REAL-params model whose kernel path matches the
#: transformer tp rules (mlp/up/kernel -> sharded on dim 1 over tp)
def _mesh_swap_model(scale: float):
    kernel = np.full((4, 8), scale, np.float32)

    def fn(p, xs):
        return [xs[0] @ p["mlp"]["up"]["kernel"]]

    return fn, {"mlp": {"up": {"kernel": kernel}}}


@pytest.fixture
def _swap_models():
    FAULTS.reset()
    for name, scale in (("shard_m1", 0.5), ("shard_m2", 1.25)):
        fn, params = _mesh_swap_model(scale)
        register_jax_model(name, fn, params)
    yield
    FAULTS.reset()
    unregister_jax_model("shard_m1")
    unregister_jax_model("shard_m2")


def _swap_pipe(extra: str = ""):
    pipe = parse_pipeline(
        "appsrc name=src ! tensor_filter name=f framework=jax-xla "
        "model=shard_m1 mesh=dp:2,tp:2 is-updatable=true "
        f"max-batch=2 batch-timeout=20 {extra}! tensor_sink name=out",
        name="meshswap",
    )
    pipe.start()
    return pipe


def _wait_outs(pipe, n, timeout=30.0):
    t0 = time.time()
    while len(pipe["out"].frames) < n and time.time() - t0 < timeout:
        time.sleep(0.01)
    assert len(pipe["out"].frames) >= n, (
        f"{len(pipe['out'].frames)}/{n} outputs")


def _vals(pipe):
    return [float(np.asarray(f.tensors[0])[0]) for f in pipe["out"].frames]


class TestShardedHotSwap:
    OLD = 4 * 0.5   # x @ K with x = ones(4): each out elem = sum * scale
    NEW = 4 * 1.25

    def test_staged_swap_is_atomic_across_the_mesh(self, _swap_models):
        """The swap is ONE pointer exchange after the new params landed
        on every mesh device: outputs are bit-exactly the old model's
        before it and the new model's after — never a torn mix."""
        pipe = _swap_pipe()
        try:
            for _ in range(4):
                pipe["src"].push(np.ones((4,), np.float32))
            _wait_outs(pipe, 4)
            ticket = pipe.reload_model("f", "shard_m2")
            assert ticket.wait_staged(30) and ticket.ok, ticket.error
            for _ in range(4):
                pipe["src"].push(np.ones((4,), np.float32))
            assert ticket.wait_applied(10)
            pipe["src"].end_of_stream()
            pipe.wait(30)
            h = pipe.health()["f"]
            assert h["swaps"] == 1 and h["swap_failures"] == 0
            assert h["restarts"] == 0
            assert h["mesh_devices"] == 4  # still the same serving mesh
            vals = _vals(pipe)
            assert vals[:4] == [self.OLD] * 4
            assert vals[4:] == [self.NEW] * 4
            # no torn half-mesh state: every output is exactly one
            # model's — a partially-staged mesh would produce neither
            assert all(v in (self.OLD, self.NEW) for v in vals)
            # the ACTIVE backend's params are genuinely sharded across
            # the mesh (the staged instance inherited the mesh config)
            spans = [
                len(leaf.sharding.device_set)
                for leaf in jax.tree.leaves(pipe["f"].backend._params)
            ]
            assert max(spans) > 1
        finally:
            pipe.stop()

    def test_staging_failure_keeps_old_mesh_serving(self, _swap_models):
        pipe = _swap_pipe()
        try:
            FAULTS.arm("filter.reload.load",
                       exc=RuntimeError("injected sharded staging fault"))
            pipe["src"].push(np.ones((4,), np.float32))
            ticket = pipe.reload_model("f", "shard_m2")
            assert ticket.wait_staged(30)
            assert not ticket.ok and ticket.state == "failed"
            pipe["src"].push(np.ones((4,), np.float32))
            pipe["src"].end_of_stream()
            pipe.wait(30)
            h = pipe.health()["f"]
            assert h["swap_failures"] == 1 and h["swaps"] == 0
            assert h["restarts"] == 0
            assert _vals(pipe) == [self.OLD] * 2  # old mesh, zero loss
        finally:
            pipe.stop()

    def test_post_swap_burst_rolls_back_to_old_mesh(self, _swap_models):
        """Observation-window rollback restores the RETAINED old sharded
        backend: the faulted frames are served by it (zero loss), the
        failed mesh backend is discarded."""
        pipe = _swap_pipe(
            extra="observation-window=60 rollback-error-burst=2 ")
        try:
            pipe["src"].push(np.ones((4,), np.float32))
            _wait_outs(pipe, 1)
            ticket = pipe.reload_model("f", "shard_m2")
            assert ticket.wait_staged(30) and ticket.ok, ticket.error
            FAULTS.arm("filter.reload.post",
                       exc=RuntimeError("new sharded model is broken"))
            for _ in range(4):
                pipe["src"].push(np.ones((4,), np.float32))
            pipe["src"].end_of_stream()
            pipe.wait(30)
            h = pipe.health()["f"]
            assert h["swaps"] == 1 and h["rollbacks"] == 1
            assert h["model_version"] == 0 and h["restarts"] == 0
            assert ticket.state == "rolled-back"
            # zero frame loss: every post-swap frame was served by the
            # retained OLD sharded backend
            assert _vals(pipe) == [self.OLD] * 5
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# Sharded-aware feed & pooling
# ---------------------------------------------------------------------------
class TestShardedFeedAndPool:
    def test_device_pool_placement_domains_never_cross(self):
        """Regression pin (satellite bugfix): two placements cycling the
        SAME (shape, dtype) never exchange buffers — a replicated
        carcass is never handed to a dp-sharded caller."""
        pool = DeviceBufferPool(max_per_key=4)
        a = pool.acquire((8,), np.float32, placement=("mesh", "dp:2"))
        pool.release(a, placement=("mesh", "dp:2"))
        b = pool.acquire((8,), np.float32, placement=("dev", "cpu", 0))
        assert b is not a, "buffer crossed placement domains"
        pool.release(b, placement=("dev", "cpu", 0))
        # same-domain reuse still works, per domain
        a2 = pool.acquire((8,), np.float32, placement=("mesh", "dp:2"))
        b2 = pool.acquire((8,), np.float32, placement=("dev", "cpu", 0))
        assert a2 is a and b2 is b
        assert pool.reused == 2 and pool.allocated == 2
        # release must key on the SAME token (derived per call)
        pool.release(a2, placement=("mesh", "dp:2"))
        assert pool.acquire((8,), np.float32) is not a2  # no-placement ring

    def test_staging_placement_tokens_distinguish_mesh_from_device(self):
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER) as plain, \
                SingleShot(framework="jax-xla", model="zoo",
                           custom=TRANSFORMER, mesh="dp:2") as sharded:
            t_plain = plain.backend.staging_placement()
            t_shard = sharded.backend.staging_placement()
        assert t_plain is not None and t_shard is not None
        assert t_plain != t_shard
        assert t_shard[0] == "mesh" and "dp:2" in t_shard[1]

    def test_ingest_lane_stages_to_sharded_layout(self, rng):
        """Host frames through the staging lane land DIRECTLY in the dp
        NamedSharding (one scatter on the lane thread, none on
        dispatch), odd tail batches pad to the dp-divisible bucket, and
        outputs stay bit-identical to unsharded serving."""
        frames = [_tokens(rng, 1)[0] for _ in range(6)]

        def run(mesh_tok):
            pipe = parse_pipeline(
                "appsrc name=src ! "
                f"tensor_filter name=f framework=jax-xla model=zoo "
                f"custom={TRANSFORMER} {mesh_tok}ingest-lane=on "
                "max-batch=4 batch-timeout=30 ! tensor_sink name=out",
                name="meshlane",
            )
            pipe.start()
            for f in frames:
                pipe["src"].push(np.asarray(f))  # host frames: lane path
            pipe["src"].end_of_stream()
            pipe.wait(timeout=120)
            outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
            be = pipe["f"].backend
            scatters = getattr(be, "mesh_scatters", 0)
            pipe.stop()
            return outs, scatters

        plain, _ = run("")
        sharded, scatters = run("mesh=dp:4 ")
        assert len(plain) == len(sharded) == 6
        for a, b in zip(plain, sharded):
            np.testing.assert_allclose(b, a, rtol=2e-4, atol=2e-4)
        # the lane really scattered host batches onto the mesh (incl.
        # the padded 2->4 tail)
        assert scatters >= 2

    def test_window_readiness_means_all_shards_not_shard_zero(self):
        """CompletionWindow contract on a mesh: a parked batch whose
        shard 0 completed but shard 1 did not is NOT ready — no output
        may emit until EVERY shard landed."""
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=64 ! tensor_filter name=f "
            "framework=async-sim custom=manual:1,mesh_dp:2 "
            "max-batch=2 batch-timeout=10 dispatch-depth=4 ! "
            "tensor_sink name=out",
            name="meshwin",
        )
        pipe.start()
        try:
            be = pipe["f"].backend
            pipe["src"].push(np.float32([1.0]))
            pipe["src"].push(np.float32([2.0]))
            # wait for the batch to be dispatched to both shard servers
            t0 = time.time()
            while time.time() - t0 < 10:
                with be._cv:
                    if (len(be._pending) >= 2 and be._pending[0]
                            and be._pending[1]):
                        break
                time.sleep(0.01)
            assert be.release_one(0)   # shard 0 completes...
            time.sleep(0.4)
            assert len(pipe["out"].frames) == 0, (
                "output emitted with only shard 0 ready")
            assert be.release_one(1)   # ...now ALL shards are ready
            _wait_outs(pipe, 2)
            vals = sorted(
                float(np.asarray(f.tensors[0])[0])
                for f in pipe["out"].frames)
            assert vals == [3.0, 5.0]  # y = 2x + 1
        finally:
            pipe["src"].end_of_stream()
            pipe.stop()


# ---------------------------------------------------------------------------
# Sharded continuous batching (slot engine under the mesh)
# ---------------------------------------------------------------------------
class TestShardedSlotEngine:
    def test_single_occupant_parity_vs_generate(self, rng):
        """A tp-sharded slot engine's single occupant is bit-identical
        to seed ``generate:<N>`` one-shot serving."""
        prompt = _tokens(rng, 1, t=8)
        with SingleShot(framework="jax-xla", model="zoo",
                        custom=TRANSFORMER + ",generate:5") as ss:
            want = np.asarray(ss.invoke_batch([prompt])[0])  # (1, 13)
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator name=gen slots=2 "
            f"mesh=tp:2 custom={TRANSFORMER} max-new=5 chunk=2 ! "
            "tensor_sink name=out",
            name="meshslot",
        )
        pipe.start()
        try:
            pipe["src"].push(prompt)
            pipe["src"].end_of_stream()
            pipe.wait(timeout=120)
            toks = np.concatenate(
                [np.asarray(f.tensors[0]) for f in pipe["out"].frames
                 if f.tensors], axis=1)
            h = pipe.health()["gen"]
        finally:
            pipe.stop()
        np.testing.assert_array_equal(toks, want[:, 8:])
        assert h["gen_completed"] == 1
        assert h["mesh_tp"] == 2 and h["mesh_devices"] == 2

    def test_generator_mesh_requires_slots_and_tp_only(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator slots=0 mesh=tp:2 "
            f"custom={TRANSFORMER} ! tensor_sink name=out")
        with pytest.raises(Exception, match="slots >= 1"):
            pipe.start()
        pipe.stop()
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_generator slots=2 mesh=dp:2 "
            f"custom={TRANSFORMER} ! tensor_sink name=out")
        with pytest.raises(Exception, match="tp only"):
            pipe.start()
        pipe.stop()


# ---------------------------------------------------------------------------
# JIT-cache hygiene: the backend compile cache is LRU-bounded (shared
# core/slots.lru_bucket discipline) so a mesh-/flex-shape sweep cannot
# grow tracing caches unbounded
# ---------------------------------------------------------------------------
def test_sharded_jit_cache_bounded_under_shape_sweep():
    register_jax_model("shard_sweep", lambda p, xs: [xs[0] * 2.0], None)
    try:
        with SingleShot(framework="jax-xla", model="shard_sweep",
                        mesh="dp:2") as s:
            be = s.backend
            cap = be.JIT_CACHE_MAX
            for n in range(1, cap + 20):
                out = s.invoke([np.full((n,), 1.0, np.float32)])
                assert float(np.asarray(out[0])[0]) == 2.0
            assert len(be._jit_cache) <= cap, (
                f"compile cache grew to {len(be._jit_cache)} > {cap}")
            # evicted shapes retrace transparently
            out = s.invoke([np.full((1,), 3.0, np.float32)])
            assert float(np.asarray(out[0])[0]) == 6.0
    finally:
        unregister_jax_model("shard_sweep")
