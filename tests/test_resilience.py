"""Resilience layer: retry/backoff (fake clock), circuit breakers,
error-policy truth table, fault-injected end-to-end recovery, and the
no-silent-except lint gate.

All tier-1 fast: fake clocks for anything time-shaped, real backoffs
capped at tens of milliseconds, no sleeps > 0.2s.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.resilience import (
    FAULTS,
    CircuitBreaker,
    CircuitOpenError,
    FatalError,
    RetryPolicy,
    TransientError,
    is_transient,
)
from nnstreamer_tpu.elements.basic import AppSrc, TensorSink
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import (
    ElementError,
    SourceElement,
    TransformElement,
)
from nnstreamer_tpu.pipeline.pipeline import Pipeline


@pytest.fixture(autouse=True)
def _clean_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t
        self.sleeps = []

    def __call__(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


# ---------------------------------------------------------------------------
# error classification
# ---------------------------------------------------------------------------
class TestClassification:
    def test_transient_types(self):
        for e in (ConnectionError("x"), TimeoutError("x"),
                  BrokenPipeError("x"), OSError("x"), TransientError("x")):
            assert is_transient(e), e

    def test_fatal_types(self):
        for e in (ValueError("x"), TypeError("x"), KeyError("x"),
                  NotImplementedError("x"), FatalError("x")):
            assert not is_transient(e), e

    def test_unknown_defaults_transient(self):
        class Weird(Exception):
            pass

        assert is_transient(Weird("x"))

    def test_marker_attribute_wins(self):
        e = ValueError("x")
        e.nns_transient = True
        assert is_transient(e)
        e2 = ConnectionError("x")
        e2.nns_transient = False
        assert not is_transient(e2)


# ---------------------------------------------------------------------------
# RetryPolicy (fake clock — zero real sleeping)
# ---------------------------------------------------------------------------
class TestRetryPolicy:
    def test_backoff_sequence_no_jitter(self):
        p = RetryPolicy(max_attempts=5, base_delay_s=0.05, multiplier=2.0,
                        max_delay_s=0.15, jitter=0.0)
        assert [p.delay_for(k) for k in (1, 2, 3, 4)] == [
            0.05, 0.10, 0.15, 0.15]  # capped

    def test_jitter_deterministic_per_seed(self):
        a = RetryPolicy(jitter=0.5, seed=42)
        b = RetryPolicy(jitter=0.5, seed=42)
        assert [a.delay_for(k) for k in range(1, 5)] == [
            b.delay_for(k) for k in range(1, 5)]

    def test_retries_transient_until_success(self):
        clk = FakeClock()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("blip")
            return "ok"

        p = RetryPolicy(max_attempts=5, base_delay_s=0.1, jitter=0.0)
        assert p.call(flaky, sleep=clk.sleep, clock=clk) == "ok"
        assert len(calls) == 3
        assert clk.sleeps == [0.1, 0.2]  # exponential, fake-slept

    def test_fatal_not_retried(self):
        clk = FakeClock()
        calls = []

        def broken():
            calls.append(1)
            raise ValueError("bad schema")

        p = RetryPolicy(max_attempts=5, jitter=0.0)
        with pytest.raises(ValueError):
            p.call(broken, sleep=clk.sleep, clock=clk)
        assert len(calls) == 1 and clk.sleeps == []

    def test_attempts_exhausted_reraises_last(self):
        p = RetryPolicy(max_attempts=3, base_delay_s=0.01, jitter=0.0)
        clk = FakeClock()
        with pytest.raises(ConnectionError):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("down")),
                   sleep=clk.sleep, clock=clk)
        assert len(clk.sleeps) == 2  # 3 attempts -> 2 backoffs

    def test_deadline_budget_stops_retries(self):
        clk = FakeClock()
        calls = []

        def flaky():
            calls.append(1)
            clk.t += 0.4  # each attempt burns 0.4s of budget
            raise TimeoutError("slow")

        p = RetryPolicy(max_attempts=10, base_delay_s=0.3, jitter=0.0,
                        deadline_s=1.0)
        with pytest.raises(TimeoutError):
            p.call(flaky, sleep=clk.sleep, clock=clk)
        # 0.4 + 0.3 backoff + 0.4 = 1.1 > 1.0 -> no third attempt
        assert len(calls) == 2

    def test_on_retry_callback(self):
        seen = []
        p = RetryPolicy(max_attempts=3, base_delay_s=0.05, jitter=0.0)
        clk = FakeClock()
        with pytest.raises(ConnectionError):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                   on_retry=lambda a, e, d: seen.append((a, d)),
                   sleep=clk.sleep, clock=clk)
        assert seen == [(1, 0.05), (2, 0.1)]


# ---------------------------------------------------------------------------
# CircuitBreaker (fake clock)
# ---------------------------------------------------------------------------
class TestCircuitBreaker:
    def make(self, clk, **kw):
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("window_s", 10.0)
        kw.setdefault("reset_timeout_s", 5.0)
        return CircuitBreaker(clock=clk, name="t", **kw)

    def test_stays_closed_below_threshold(self):
        clk = FakeClock()
        b = self.make(clk)
        b.record_failure()
        b.record_failure()
        assert b.state == "closed" and b.allow()

    def test_trips_open_at_threshold(self):
        clk = FakeClock()
        b = self.make(clk)
        for _ in range(3):
            b.record_failure()
        assert b.state == "open" and not b.allow()
        assert b.trip_count == 1

    def test_rolling_window_forgets_old_failures(self):
        clk = FakeClock()
        b = self.make(clk)
        b.record_failure()
        b.record_failure()
        clk.t += 11.0  # both fall out of the 10s window
        b.record_failure()
        assert b.state == "closed"

    def test_half_open_probe_then_close(self):
        clk = FakeClock()
        b = self.make(clk)
        for _ in range(3):
            b.record_failure()
        clk.t += 5.0
        assert b.state == "half-open"
        assert b.allow()        # the single probe slot
        assert not b.allow()    # no second probe
        b.record_success()
        assert b.state == "closed" and b.allow()

    def test_half_open_probe_failure_reopens(self):
        clk = FakeClock()
        b = self.make(clk)
        for _ in range(3):
            b.record_failure()
        clk.t += 5.0
        assert b.allow()
        b.record_failure()
        assert b.state == "open"
        assert b.trip_count == 2
        clk.t += 4.9
        assert not b.allow()
        clk.t += 0.2
        assert b.allow()  # half-open again

    def test_call_wrapper_raises_circuit_open(self):
        clk = FakeClock()
        b = self.make(clk, failure_threshold=1)
        with pytest.raises(RuntimeError):
            b.call(lambda: (_ for _ in ()).throw(RuntimeError("x")))
        with pytest.raises(CircuitOpenError):
            b.call(lambda: "never runs")

    def test_circuit_open_error_is_transient(self):
        assert is_transient(CircuitOpenError("open"))

    def test_snapshot(self):
        clk = FakeClock()
        b = self.make(clk, failure_threshold=1)
        b.record_failure()
        snap = b.snapshot()
        assert snap["state"] == "open" and snap["trips"] == 1

    def test_stale_inflight_success_does_not_close_open_breaker(self):
        # symmetric to the stale-failure case: a request sent BEFORE the
        # trip completing while the breaker is open must not bypass
        # reset_timeout/half-open probing (pipelined clients share one
        # breaker across in-flight requests)
        clk = FakeClock()
        b = self.make(clk)
        for _ in range(3):
            b.record_failure()
        assert b.state == "open"
        b.record_success()  # stale in-flight success
        assert b.state == "open" and not b.allow()
        clk.t += 5.0
        assert b.allow()        # half-open probing still required
        b.record_success()      # the real probe closes it
        assert b.state == "closed"

    def test_stale_inflight_failure_is_not_a_probe_failure(self):
        # a request older than the open window (timeout > reset_timeout)
        # failing during half-open must NOT re-open the breaker: no
        # probe was granted, so there is nothing to fail
        clk = FakeClock()
        b = self.make(clk)
        for _ in range(3):
            b.record_failure()
        clk.t += 5.0
        assert b.state == "half-open"
        b.record_failure()  # stale in-flight failure, no allow() yet
        assert b.state == "half-open" and b.trip_count == 1
        assert b.allow()  # the real probe is still available
        b.record_success()
        assert b.state == "closed"


# ---------------------------------------------------------------------------
# FaultInjector determinism
# ---------------------------------------------------------------------------
class TestFaultInjector:
    def site_hits(self, **arm_kw):
        FAULTS.arm("t.site", **arm_kw)
        hits = []
        for i in range(20):
            try:
                FAULTS.check("t.site")
                hits.append(0)
            except BaseException:
                hits.append(1)
        FAULTS.disarm("t.site")
        return hits

    def test_unarmed_is_noop(self):
        FAULTS.check("never.armed")  # must not raise

    def test_rate_deterministic_same_seed(self):
        a = self.site_hits(rate=0.4, seed=11)
        b = self.site_hits(rate=0.4, seed=11)
        assert a == b and 0 < sum(a) < 20

    def test_every_strictly_periodic(self):
        hits = self.site_hits(every=4)
        assert hits == [1 if i % 4 == 0 else 0 for i in range(20)]

    def test_after_and_times(self):
        hits = self.site_hits(rate=1.0, after=3, times=2)
        assert hits == [0, 0, 0, 1, 1] + [0] * 15

    def test_custom_exception_and_stats(self):
        FAULTS.arm("t.exc", exc=BrokenPipeError, every=2)
        with pytest.raises(BrokenPipeError):
            FAULTS.check("t.exc")
        FAULTS.check("t.exc")
        assert FAULTS.stats("t.exc") == {"calls": 2, "fired": 1}

    def test_callback_controls_everything(self):
        FAULTS.arm("t.cb", callback=lambda i: OSError("x") if i == 1 else None)
        FAULTS.check("t.cb")
        with pytest.raises(OSError):
            FAULTS.check("t.cb")
        FAULTS.check("t.cb")

    def test_reset_clears_all(self):
        FAULTS.arm("t.a", rate=1.0)
        FAULTS.reset()
        FAULTS.check("t.a")
        assert not FAULTS.armed_sites()


# ---------------------------------------------------------------------------
# error-policy truth table (pipeline supervision)
# ---------------------------------------------------------------------------
class Pass(TransformElement):
    """Counting identity element used as the supervision target."""

    FACTORY_NAME = "pass"

    def __init__(self, name=None):
        super().__init__(name)
        self.starts = 0
        self.stops = 0

    def start(self):
        self.starts += 1

    def stop(self):
        self.stops += 1

    def transform(self, frame):
        return frame


def run_policy_pipeline(policy, n=9, site_kw=None, el_props=None,
                        expect_error=None):
    """One appsrc ! Pass(policy) ! sink run with faults armed on the
    Pass element's scheduler site; returns (pipe, sink frames, warnings)."""
    pipe = Pipeline("tp")
    src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
    mid.set_property("error-policy", policy)
    for k, v in (el_props or {}).items():
        mid.set_property(k, v)
    pipe.chain(src, mid, sink)
    warnings = []
    pipe.add_bus_watcher(
        lambda m: warnings.append(m) if m.kind == "warning" else None)
    if site_kw:
        FAULTS.arm("element.mid.handle_frame", **site_kw)
    pipe.start()
    for i in range(n):
        src.push(np.float32([i]))
    src.end_of_stream()
    if expect_error is None:
        pipe.wait(timeout=20)
    else:
        with pytest.raises(expect_error):
            pipe.wait(timeout=20)
    return pipe, sink, warnings


class TestErrorPolicyTruthTable:
    def test_invalid_policy_rejected(self):
        el = Pass("x")
        with pytest.raises((ElementError, ValueError)):
            el.set_property("error-policy", "retry-forever")

    def test_invalid_degrade_rejected(self):
        from nnstreamer_tpu.elements.query import TensorQueryClient

        q = TensorQueryClient("q")
        with pytest.raises((ElementError, ValueError)):
            q.set_property("degrade", "pass-through")  # typo must fail EARLY

    def test_fail_stop_default_kills_pipeline(self):
        pipe, sink, _ = run_policy_pipeline(
            "fail-stop", site_kw=dict(every=3, exc=ConnectionResetError),
            expect_error=ConnectionResetError)
        assert pipe.health()["mid"]["state"] == "failed"
        pipe.stop()

    def test_skip_drops_to_dead_letter_and_continues(self):
        pipe, sink, warnings = run_policy_pipeline(
            "skip", n=9, site_kw=dict(every=3, exc=ConnectionResetError))
        assert len(sink.frames) == 6  # every 3rd of 9 dropped
        h = pipe.health()["mid"]
        assert h["dead_letters"] == 3 and h["state"] == "finished"
        assert [m for m in warnings if m.data.get("policy") == "skip"]
        pipe.stop()

    def test_skip_dead_letter_queue_bounded(self):
        pipe, sink, _ = run_policy_pipeline(
            "skip", n=10, site_kw=dict(rate=1.0),
            el_props={"dead-letter-max": 4})
        h = pipe.health()["mid"]
        assert len(sink.frames) == 0
        assert h["dead_letters"] == 10      # lifetime counter unbounded
        assert h["dead_letter_depth"] == 4  # retention bounded
        pipe.stop()

    def test_skip_dead_letter_max_zero_retains_nothing(self):
        # 0 = count drops but pin NO frame payloads in memory
        pipe, sink, _ = run_policy_pipeline(
            "skip", n=5, site_kw=dict(rate=1.0),
            el_props={"dead-letter-max": 0})
        h = pipe.health()["mid"]
        assert h["dead_letters"] == 5 and h["dead_letter_depth"] == 0
        pipe.stop()

    def test_restart_retries_frame_zero_loss(self):
        pipe, sink, warnings = run_policy_pipeline(
            "restart", n=8,
            site_kw=dict(every=4, times=2, exc=TimeoutError),
            el_props={"restart-backoff": 0.01, "max-restarts": 10})
        assert len(sink.frames) == 8  # faulted frames retried, zero loss
        h = pipe.health()["mid"]
        assert h["restarts"] == 2 and h["state"] == "finished"
        assert pipe["mid"].stops >= 2 and pipe["mid"].starts >= 3
        assert [m for m in warnings if "restart" in m.data]
        pipe.stop()

    def test_restart_degrades_to_fail_stop_after_budget(self):
        pipe, sink, warnings = run_policy_pipeline(
            "restart", n=3, site_kw=dict(rate=1.0, exc=ConnectionResetError),
            el_props={"restart-backoff": 0.0, "max-restarts": 2},
            expect_error=ConnectionResetError)
        h = pipe.health()["mid"]
        assert h["restarts"] == 2
        assert h["state"] == "failed"  # degraded, then the error surfaced
        assert [m for m in warnings if m.data.get("degraded")]
        pipe.stop()

    def test_restart_fatal_error_dead_letters_instead(self):
        # poison frames (fatal classification) must not burn the restart
        # budget — a restart cannot fix bad input
        pipe, sink, warnings = run_policy_pipeline(
            "restart", n=6, site_kw=dict(every=3, exc=ValueError),
            el_props={"max-restarts": 1})
        h = pipe.health()["mid"]
        assert len(sink.frames) == 4       # 2 poison frames dropped
        assert h["dead_letters"] == 2
        assert h["restarts"] == 0          # budget untouched
        assert h["state"] == "finished"
        pipe.stop()

    def test_restart_window_refills_budget(self):
        # two isolated glitches separated by more than restart-window
        # must NOT accumulate against max-restarts=1 (always-on contract)
        pipe = Pipeline("tw")
        src, mid, sink = AppSrc("src"), Pass("mid"), TensorSink("out")
        mid.set_property("error-policy", "restart")
        mid.set_property("max-restarts", 1)
        mid.set_property("restart-backoff", 0.0)
        mid.set_property("restart-window", 0.05)
        pipe.chain(src, mid, sink)
        FAULTS.arm("element.mid.handle_frame", every=2, times=2,
                   exc=TimeoutError)  # faults on the 1st and 3rd call
        pipe.start()
        src.push(np.float32([0]))  # fault -> restart 1/1
        deadline = time.monotonic() + 5
        while (pipe.health()["mid"]["restarts"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.005)      # restart observed before the gap starts
        time.sleep(0.08)           # sustained health > restart-window
        src.push(np.float32([1]))
        src.push(np.float32([2]))  # fault again -> budget had refilled
        src.end_of_stream()
        pipe.wait(timeout=20)
        h = pipe.health()["mid"]
        assert len(sink.frames) == 3
        assert h["restarts"] == 2 and h["restarts_window"] == 1
        assert h["state"] == "finished"
        pipe.stop()

    def test_events_remain_fail_stop_under_skip(self):
        # EOS/caps handling is outside the policy boundary: an element
        # whose frame path skips still completes EOS normally
        pipe, sink, _ = run_policy_pipeline(
            "skip", n=4, site_kw=dict(rate=1.0))
        assert pipe.health()["mid"]["state"] == "finished"
        pipe.stop()

    def test_skip_isolates_poison_within_micro_batch(self):
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.pipeline.element import Element

        class BatchScaler(Element):
            """Micro-batching element that chokes on value 7."""

            FACTORY_NAME = "batchscaler"
            preferred_batch = 4
            batch_wait_s = 0.05  # let batches actually form

            def handle_frame(self, pad, frame):
                return self.handle_frame_batch(pad, [frame])

            def handle_frame_batch(self, pad, frames):
                if any(float(f.tensors[0][0]) == 7.0 for f in frames):
                    raise RuntimeError("poison value")
                return [
                    (0, TensorFrame([f.tensors[0] * 2])) for f in frames
                ]

        pipe = Pipeline("iso")
        src, mid, sink = AppSrc("src"), BatchScaler("mid"), TensorSink("out")
        mid.set_property("error-policy", "skip")
        pipe.chain(src, mid, sink)
        pipe.start()
        n = 8
        for i in range(n):
            src.push(np.float32([i]))
        src.end_of_stream()
        pipe.wait(timeout=20)
        h = pipe.health()["mid"]
        vals = sorted(float(f.tensors[0][0]) for f in sink.frames)
        # ONLY frame 7 is lost — its batchmates survive via isolation
        assert vals == [i * 2.0 for i in range(n) if i != 7]
        assert h["dead_letters"] == 1
        pipe.stop()

    def test_block_split_skip_processes_each_logical_frame_once(self):
        # a stateful non-batch-aware element + block ingest + skip: the
        # poisoned logical frame is dropped alone and NO frame is
        # processed twice (no batch-call-then-replay on the split path)
        from nnstreamer_tpu.pipeline.element import TransformElement

        class StatefulDoubler(TransformElement):
            FACTORY_NAME = "statefuldoubler"

            def __init__(self, name=None):
                super().__init__(name)
                self.seen = []

            def transform(self, frame):
                v = float(frame.tensors[0][0])
                self.seen.append(v)
                if v == 2.0:
                    raise RuntimeError("poison")
                return frame

        pipe = Pipeline("blk")
        src, mid, sink = AppSrc("src"), StatefulDoubler("mid"), TensorSink("out")
        mid.set_property("error-policy", "skip")
        pipe.chain(src, mid, sink)
        pipe.start()
        src.push_block(np.arange(5, dtype=np.float32).reshape(5, 1))
        src.end_of_stream()
        pipe.wait(timeout=20)
        assert mid.seen == [0.0, 1.0, 2.0, 3.0, 4.0]  # once each, in order
        assert len(sink.frames) == 4
        assert pipe.health()["mid"]["dead_letters"] == 1
        pipe.stop()

    def test_source_restart_fatal_fails_fast(self):
        class BuggyCam(SourceElement):
            FACTORY_NAME = "buggycam"

            def frames(self):
                raise ValueError("deterministic bug")
                yield  # pragma: no cover

        pipe = Pipeline("bug")
        cam, sink = BuggyCam("cam"), TensorSink("out")
        cam.set_property("error-policy", "restart")
        pipe.chain(cam, sink)
        pipe.start()
        with pytest.raises(ValueError):
            pipe.wait(timeout=20)
        assert pipe.health()["cam"]["restarts"] == 0  # no crash-loop
        pipe.stop()

    def test_source_restart_reopens_flaky_camera(self):
        class FlakyCam(SourceElement):
            FACTORY_NAME = "flakycam"

            def __init__(self, name=None):
                super().__init__(name)
                self.cursor = 0
                self.crashed = False

            def frames(self):
                from nnstreamer_tpu.core.buffer import TensorFrame

                while self.cursor < 10:
                    if self.cursor == 4 and not self.crashed:
                        self.crashed = True
                        raise ConnectionError("camera unplugged")
                    i = self.cursor
                    self.cursor += 1
                    yield TensorFrame([np.float32([i])])

        pipe = Pipeline("cam")
        cam, sink = FlakyCam("cam"), TensorSink("out")
        cam.set_property("error-policy", "restart")
        cam.set_property("restart-backoff", 0.01)
        pipe.chain(cam, sink)
        pipe.start()
        pipe.wait(timeout=20)
        assert len(sink.frames) == 10  # resumed from its cursor, no dupes
        assert pipe.health()["cam"]["restarts"] == 1
        pipe.stop()


# ---------------------------------------------------------------------------
# wait(timeout) teardown contract
# ---------------------------------------------------------------------------
def test_wait_timeout_stops_workers():
    pipe = Pipeline("hang")
    src, sink = AppSrc("src"), TensorSink("out")
    pipe.chain(src, sink)
    pipe.start()
    src.push(np.float32([1]))  # no EOS -> wait must time out
    with pytest.raises(TimeoutError):
        pipe.wait(timeout=0.15)
    deadline = time.monotonic() + 2.0
    while time.monotonic() < deadline:
        if not any(t.name in ("src", "out") and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.01)
    leaked = [t.name for t in threading.enumerate()
              if t.name in ("src", "out") and t.is_alive()]
    assert not leaked, f"wait(timeout) leaked workers: {leaked}"


# ---------------------------------------------------------------------------
# tcp query pool hygiene (satellite audit)
# ---------------------------------------------------------------------------
class TestTcpPoolHygiene:
    def make_server(self, sid):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            "connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_recv_failure_evicts_socket_from_pool(self):
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.distributed.tcp_query import TcpQueryConnection

        server, port = self.make_server(941)
        conn = TcpQueryConnection("localhost", port, timeout=5.0, nconns=2)
        try:
            conn.invoke(TensorFrame([np.float32([1])]))
            assert len(conn._free) == 1
            FAULTS.arm("tcp_query.recv", times=1, exc=ConnectionResetError)
            with pytest.raises(ConnectionResetError):
                conn.invoke(TensorFrame([np.float32([2])]))
            # the broken socket must be CLOSED and GONE, not pooled
            assert len(conn._free) == 0 and conn._live == 0
            FAULTS.reset()
            out = conn.invoke(TensorFrame([np.float32([3])]))  # fresh dial
            assert float(out.tensors[0][0]) == 6.0
        finally:
            conn.close()
            server.stop()

    def test_stale_pooled_socket_send_retries_fresh(self):
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.distributed.tcp_query import TcpQueryConnection

        server, port = self.make_server(942)
        conn = TcpQueryConnection("localhost", port, timeout=5.0, nconns=2)
        try:
            conn.invoke(TensorFrame([np.float32([1])]))  # pools one socket
            # a send-phase failure on the REUSED socket is retried once
            # on a fresh dial — the caller never sees it
            FAULTS.arm("tcp_query.send", times=1, exc=BrokenPipeError)
            out = conn.invoke(TensorFrame([np.float32([2])]))
            assert float(out.tensors[0][0]) == 4.0
            assert FAULTS.stats("tcp_query.send")["fired"] == 1
        finally:
            conn.close()
            server.stop()


# ---------------------------------------------------------------------------
# edgesrc failover dial
# ---------------------------------------------------------------------------
def test_edgesrc_dest_hosts_failover_dial():
    from nnstreamer_tpu.distributed.tcp_edge import TcpEdgeServer
    from nnstreamer_tpu.elements.edge import EdgeSrc

    srv = TcpEdgeServer(port=0)
    try:
        el = EdgeSrc("esrc")
        el.set_property("connect-type", "tcp")
        # first target refuses; failover dials the live one
        el.set_property("dest-hosts", f"localhost:1,localhost:{srv.port}")
        el.set_property("topic", "tv")
        el.start()
        deadline = time.monotonic() + 2.0
        while srv.subscriber_count("tv") == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.subscriber_count("tv") == 1
        el.stop()
    finally:
        srv.close()


def test_edgesrc_direct_dead_targets_fail_loudly():
    # gRPC channels dial lazily; with dest-hosts failover configured the
    # dial must PROBE, so dead targets fail start() instead of silently
    # "connecting" to the first dead endpoint
    from nnstreamer_tpu.elements.edge import EdgeSrc

    el = EdgeSrc("esrc")
    el.set_property("connect-type", "direct")
    el.set_property("dest-hosts", "localhost:1,localhost:2")
    with pytest.raises(ConnectionError):
        el.start()


def test_remote_application_error_does_not_trip_breaker():
    # a healthy server answering with error REPLIES (poison frames) must
    # never open its breaker or mark it down — only transport faults do
    from nnstreamer_tpu.core.resilience import RemoteApplicationError
    from nnstreamer_tpu.elements.query import TensorQueryClient, _PoolState

    q = TensorQueryClient("q")
    q.set_property("breaker-threshold", 2)
    q.set_property("retries", 0)
    q.set_property("retry-backoff", 0.0)

    class FakeConn:
        addr = "fake:1"

        def invoke(self, frame, timeout):
            raise RemoteApplicationError("undecodable frame")

    q._pstate = _PoolState((FakeConn(),), (("fake", 1),), 0)
    q._stopped = False
    for _ in range(5):
        with pytest.raises(RemoteApplicationError):
            q._invoke_failover(object(), 0)
    snap = q.health_info()["breakers"]["fake:1"]
    assert snap["state"] == "closed" and snap["trips"] == 0
    assert is_transient(RemoteApplicationError("x"))  # still retryable


def test_mid_stream_failure_counts_against_breaker():
    # a server that repeatedly dies mid-stream must lose its breaker
    # (record_success on the first answer must not immunize the crash)
    from nnstreamer_tpu.core.buffer import TensorFrame
    from nnstreamer_tpu.elements.query import TensorQueryClient, _PoolState

    class MidStreamCrash:
        addr = "fake:1"

        def invoke_stream(self, frame, timeout):
            yield TensorFrame([np.float32([1])])
            raise ConnectionResetError("mid-stream crash")

    q = TensorQueryClient("q")
    q.set_property("breaker-threshold", 2)
    q.set_property("stream", True)
    q._pstate = _PoolState((MidStreamCrash(),), (("fake", 1),), 0)
    q._stopped = False
    frame = TensorFrame([np.float32([0])])
    for _ in range(2):
        with pytest.raises(ConnectionResetError):
            list(q._stream_invoke(frame))
    snap = q.health_info()["breakers"]["fake:1"]
    assert snap["state"] == "open" and snap["trips"] == 1


def test_edgesrc_bad_dest_hosts_rejected():
    from nnstreamer_tpu.elements.edge import EdgeSrc

    el = EdgeSrc("esrc")
    el.set_property("dest-hosts", "nonsense")
    with pytest.raises(ElementError):
        el.start()


# ---------------------------------------------------------------------------
# lint gate: no silent exception swallowing
# ---------------------------------------------------------------------------
def test_no_bare_except():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import check_no_bare_except
    finally:
        sys.path.pop(0)
    bad = check_no_bare_except.scan()
    assert not bad, f"silent exception handlers: {bad}"


# ---------------------------------------------------------------------------
# chaos: fault-injected end-to-end offload with failover (acceptance)
# ---------------------------------------------------------------------------
class TestChaosEndToEnd:
    def make_server(self, sid):
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            "connect-type=tcp ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={sid}")
        pipe.start()
        return pipe, pipe["ssrc"].props["port"]

    def test_flaky_transport_and_server_kill_zero_loss(self):
        """30% transient send faults + one mid-stream server kill with a
        failover remote: the run completes with zero frame loss beyond
        the configured skip drops (degrade=skip accounts every one), and
        health() shows the breaker trips.

        Retries absorb virtually all injected faults; degrade=skip is
        the accounting backstop for the probabilistic residue (a frame
        whose 6 attempts ALL draw the 30% fault), so the assertion is an
        exact identity, not a race."""
        sa, pa = self.make_server(951)
        sb, pb = self.make_server(952)
        FAULTS.arm("tcp_query.send", rate=0.30, seed=7,
                   exc=ConnectionResetError)
        # breaker-reset (0.3s) < the retries=5 backoff budget (~0.31s+),
        # so even if injected faults trip BOTH breakers, a half-open
        # probe is granted within one frame's attempt budget — the
        # breaker can never convert the whole run into skip drops
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            f"hosts=localhost:{pa},localhost:{pb} retries=5 "
            "retry-backoff=0.01 breaker-threshold=3 breaker-reset=0.3 "
            "degrade=skip timeout=5 max-in-flight=4 ! tensor_sink name=out")
        client.start()
        killed = False
        try:
            n = 40
            for i in range(n):
                client["src"].push(np.float32([i]))
                if i == 15:
                    sa.stop()  # mid-stream kill; failover to server B
                    killed = True
            client["src"].end_of_stream()
            client.wait(timeout=60)
            h = client.health()["q"]
            vals = sorted(float(f.tensors[0][0]) for f in client["out"].frames)
            # exact accounting: every pushed frame either answered
            # (correct value, no dupes) or counted as a skip drop
            assert len(vals) + h["degraded_frames"] == n, (
                f"unaccounted loss: {len(vals)} answered + "
                f"{h['degraded_frames']} skipped != {n}")
            assert set(vals) <= {i * 2.0 for i in range(n)}
            assert len(set(vals)) == len(vals)  # ordered-unique answers
            # retries must absorb nearly everything — skip is a backstop
            assert h["degraded_frames"] <= 4, h
            # the dead remote's breaker tripped and the trip is reported
            dead = h["breakers"].get(f"localhost:{pa}", {})
            assert dead.get("trips", 0) >= 1, h
            assert FAULTS.stats("tcp_query.send")["fired"] > 0
        finally:
            client.stop()
            if not killed:
                sa.stop()
            sb.stop()

    def test_local_filter_restart_chaos_zero_loss(self):
        """filter.invoke faults + error-policy=restart: the supervisor
        restarts the filter and retries, health reports the restarts."""
        FAULTS.arm("filter.invoke", every=5, times=3, exc=TimeoutError)
        pipe = parse_pipeline(
            "appsrc name=src ! "
            "tensor_filter name=f framework=scaler custom=factor:3 "
            "error-policy=restart restart-backoff=0.01 max-restarts=10 ! "
            "tensor_sink name=out")
        pipe.start()
        n = 20
        for i in range(n):
            pipe["src"].push(np.float32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=30)
        vals = sorted(float(f.tensors[0][0]) for f in pipe["out"].frames)
        assert vals == [i * 3.0 for i in range(n)]
        h = pipe.health()["f"]
        assert h["restarts"] == 3 and h["state"] == "finished"
        pipe.stop()

    def test_stream_mode_honors_degrade_skip(self):
        """stream=true: a request that fails on every remote BEFORE its
        first answer degrades per degrade= instead of killing the
        pipeline (mid-stream breaks still surface — partial output
        already left)."""
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q "
            "host=localhost port=1 stream=true retries=0 retry-backoff=0 "
            "timeout=0.3 breaker-threshold=0 degrade=skip ! "
            "tensor_sink name=out")
        client.start()
        for i in range(3):
            client["src"].push(np.float32([i]))
        client["src"].end_of_stream()
        client.wait(timeout=30)
        assert len(client["out"].frames) == 0
        assert client.health()["q"]["degraded_frames"] == 3
        client.stop()

    def test_query_client_ignores_worker_skip_policy(self):
        """The query client supervises its own errors (degrade=): with
        pipelined in-flight answers, worker-level skip would dead-letter
        the WRONG frame, so the scheduler runs it fail-stop and failures
        surface unless degrade= is set."""
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            "host=localhost port=1 retries=0 retry-backoff=0 timeout=0.3 "
            "breaker-threshold=0 error-policy=skip ! tensor_sink name=out")
        client.start()
        client["src"].push(np.float32([0]))
        client["src"].end_of_stream()
        with pytest.raises(Exception):
            client.wait(timeout=20)
        assert client.health()["q"]["dead_letters"] == 0  # nothing misfiled
        client.stop()

    def test_degrade_skip_accounts_every_drop(self):
        """degrade=skip against a dead-only remote: the stream completes,
        and loss == exactly the skipped frames (the acceptance wording:
        zero loss beyond the configured skip drops)."""
        client = parse_pipeline(
            "appsrc name=src ! tensor_query_client name=q connect-type=tcp "
            "host=localhost port=1 retries=0 retry-backoff=0 timeout=0.3 "
            "breaker-threshold=1 breaker-reset=60 degrade=skip ! "
            "tensor_sink name=out")
        client.start()
        n = 6
        for i in range(n):
            client["src"].push(np.float32([i]))
        client["src"].end_of_stream()
        client.wait(timeout=30)
        h = client.health()["q"]
        assert len(client["out"].frames) == 0
        assert h["degraded_frames"] == n  # every drop accounted
        assert h["breakers"]["localhost:1"]["trips"] >= 1
        client.stop()
