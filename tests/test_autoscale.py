"""Predictive fleet autoscaling controller (Documentation/resilience.md
"Fleet autoscaling").

Contracts pinned here:

* plan() decision truth table under a fake clock — hysteresis streak
  boundaries (fast up, slow down), per-kind cooldowns, envelope
  floor/ceiling/clamps with resize escalation, the
  one-action-in-flight-per-server invariant, stale-row exclusion, and
  the predictive path's <k-samples reactive fallback.  Every suppressed
  impulse is COUNTED (quiet != blind).
* PerfModel — exact least-squares recovery of a known linear surface,
  the readiness gate (min samples AND occupancy spread AND nonzero-TTFT
  rows), zero-TTFT exclusion from the latency fit, banked-bench rows.
* FleetController — tick/reap/dispatch accounting against NullActuator,
  failure surfacing (failed tickets and raising actuators), the
  decision snapshot, and the ``nns.autoscale.*`` registry collector
  (every sample catalogued, kinds match).
* Observatory satellites — the stale TIER below eviction (flagged rows
  stay listed and counted but are excluded from headroom/throughput
  gauges) and the bounded retired-server ledger (aggregates preserved
  exactly across eviction, loud ``retired_evicted`` counter).
* fleet_top decision column — the controller snapshot renders.
* Zero-loss live actuation — ``request_resize`` on a serving generator
  under live streams (bit-identical migration, ledger continuity) and
  the chaos-marked ``--mode autoscale`` acceptance: ramp scale-up,
  hot-tenant-burst absorption with the victim goodput floor, and a
  controller-initiated scale-down under live load with exact
  zero-lost/zero-dup verdicts.
"""

import sys
import time
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from nnstreamer_tpu.core.autoscale import (
    RESIZE,
    SCALE_DOWN,
    SCALE_UP,
    Action,
    ActionTicket,
    ControllerState,
    FleetController,
    FleetPolicy,
    NullActuator,
    PerfModel,
    plan,
)
from nnstreamer_tpu.core.fleet import FleetObservatory


# ---------------------------------------------------------------------------
# snapshot builders (the plan() contract is pure: snapshot in, actions out)
# ---------------------------------------------------------------------------
def _row(topic, addr, occupied=0, slots=4, waiting=0, stale=False,
         draining=False, tokens_per_s=0.0):
    return {"topic": topic, "addr": addr, "occupied": occupied,
            "slots": slots, "waiting": waiting, "stale": stale,
            "draining": draining, "tokens_per_s": tokens_per_s}


def _snap(*rows, headroom=None, burn=None):
    if headroom is None:
        headroom = sum(r["slots"] - r["occupied"] for r in rows
                       if not r["stale"])
    return {"servers": list(rows),
            "rollup": {"slot_headroom": headroom,
                       "slo_burn": burn or {}}}


def _policy(**kw):
    base = dict(min_servers=1, max_servers=4, occupancy_high=0.85,
                slot_headroom_min=1, burn_high=1.0, occupancy_low=0.30,
                up_streak=2, down_streak=5, cooldown_up_s=10.0,
                cooldown_down_s=30.0, cooldown_resize_s=30.0)
    base.update(kw)
    return FleetPolicy(**base)


# ---------------------------------------------------------------------------
# plan(): the decision truth table (fake clock throughout)
# ---------------------------------------------------------------------------
class TestPlanTruthTable:
    def test_up_hysteresis_boundary_and_streak_reset(self):
        pol = _policy(up_streak=3)
        st = ControllerState()
        hot = _snap(_row("a", "h:1", occupied=4), _row("b", "h:2",
                                                       occupied=4))
        assert plan(hot, pol, st, now=0.0) == []      # streak 1/3
        assert plan(hot, pol, st, now=1.0) == []      # streak 2/3
        assert st.hysteresis_holds == 2
        # pressure evaporates for one tick: the streak starts over
        calm = _snap(_row("a", "h:1", occupied=2), _row("b", "h:2"))
        assert plan(calm, pol, st, now=2.0) == []
        assert st.up_streak == 0
        assert plan(hot, pol, st, now=3.0) == []
        assert plan(hot, pol, st, now=4.0) == []
        acts = plan(hot, pol, st, now=5.0)            # streak 3/3 fires
        assert [a.kind for a in acts] == [SCALE_UP]
        assert not acts[0].predictive
        assert st.reactive_decisions == 1 and st.decisions == 1
        assert st.target_servers == 3

    def test_up_triggers_occupancy_headroom_and_burn(self):
        pol = _policy(up_streak=1)
        # occupancy trigger
        st = ControllerState()
        acts = plan(_snap(_row("a", "h:1", occupied=4)), pol, st, now=0.0)
        assert acts and "occupancy" in acts[0].reason
        # headroom trigger (occupancy below high water)
        st = ControllerState()
        acts = plan(_snap(_row("a", "h:1", occupied=2), headroom=0),
                    pol, st, now=0.0)
        assert acts and "headroom" in acts[0].reason
        # SLO-burn trigger (capacity otherwise fine)
        st = ControllerState()
        acts = plan(_snap(_row("a", "h:1", occupied=1),
                          burn={"A": 1.5}), pol, st, now=0.0)
        assert acts and "burn" in acts[0].reason

    def test_up_cooldown_paces_refire_and_is_counted(self):
        pol = _policy(up_streak=1, cooldown_up_s=10.0, max_servers=8)
        st = ControllerState()
        hot = _snap(_row("a", "h:1", occupied=4))
        assert plan(hot, pol, st, now=100.0)          # fires
        assert plan(hot, pol, st, now=105.0) == []    # cooling
        assert plan(hot, pol, st, now=109.9) == []
        assert st.cooldown_skips == 2
        assert plan(hot, pol, st, now=110.1)          # cooldown over

    def test_down_slow_streak_picks_least_loaded(self):
        pol = _policy(down_streak=5, cooldown_down_s=0.0)
        st = ControllerState()
        calm = _snap(_row("a", "h:1", occupied=1, tokens_per_s=5.0),
                     _row("b", "h:2", occupied=0, tokens_per_s=1.0),
                     _row("c", "h:3", occupied=0, tokens_per_s=9.0))
        for i in range(4):
            assert plan(calm, pol, st, now=float(i)) == []
        assert st.hysteresis_holds == 4
        acts = plan(calm, pol, st, now=4.0)
        assert [a.kind for a in acts] == [SCALE_DOWN]
        # least occupied, then least tokens/s, then address: b wins
        assert acts[0].target == "b"
        assert st.target_servers == 2

    def test_down_requires_no_waiting_and_no_burn(self):
        pol = _policy(down_streak=1)
        st = ControllerState()
        # waiting prompts block calm even at zero occupancy
        assert plan(_snap(_row("a", "h:1", waiting=1),
                          _row("b", "h:2")), pol, st, now=0.0) == []
        assert st.down_streak == 0
        # a burning tenant blocks calm
        assert plan(_snap(_row("a", "h:1"), _row("b", "h:2"),
                          burn={"A": 1.2}), pol, st, now=1.0) == []
        assert st.down_streak == 0

    def test_envelope_floor_fires_immediately(self):
        pol = _policy(min_servers=2, up_streak=5)
        st = ControllerState()
        acts = plan(_snap(_row("a", "h:1")), pol, st, now=0.0)
        assert [a.kind for a in acts] == [SCALE_UP]   # no streak wait
        assert "floor" in acts[0].reason
        assert st.target_servers == 2

    def test_envelope_ceiling_drains_immediately_paced_by_cooldown(self):
        pol = _policy(max_servers=2, cooldown_down_s=5.0)
        st = ControllerState()
        snap = _snap(_row("a", "h:1", occupied=2),
                     _row("b", "h:2", occupied=1),
                     _row("c", "h:3", occupied=2))
        acts = plan(snap, pol, st, now=0.0)
        assert [a.kind for a in acts] == [SCALE_DOWN]
        assert acts[0].target == "b" and "ceiling" in acts[0].reason
        # while the drain is in flight n_eff is already back at the
        # ceiling — no second drain
        st.inflight["b"] = SCALE_DOWN
        assert plan(snap, pol, st, now=0.1) == []
        # drain landed (b gone) but a SECOND shrink is paced by cooldown
        st.inflight.clear()
        pol2 = _policy(max_servers=1, cooldown_down_s=5.0)
        two = _snap(_row("a", "h:1", occupied=2),
                    _row("c", "h:3", occupied=2))
        assert plan(two, pol2, st, now=1.0) == []
        assert st.cooldown_skips == 1
        acts = plan(two, pol2, st, now=6.0)
        assert [a.kind for a in acts] == [SCALE_DOWN]

    def test_up_clamped_at_max_servers_is_counted(self):
        pol = _policy(up_streak=1, max_servers=2)
        st = ControllerState()
        hot = _snap(_row("a", "h:1", occupied=4),
                    _row("b", "h:2", occupied=4))
        assert plan(hot, pol, st, now=0.0) == []
        assert st.envelope_clamps == 1 and st.decisions == 0

    def test_resize_escalation_at_max_servers(self):
        pol = _policy(up_streak=1, max_servers=2, resize_max_slots=8,
                      cooldown_resize_s=10.0)
        st = ControllerState()
        hot = _snap(_row("a", "h:1", occupied=4, slots=4),
                    _row("b", "h:2", occupied=2, slots=2))
        acts = plan(hot, pol, st, now=0.0)
        assert [a.kind for a in acts] == [RESIZE]
        assert acts[0].target == "b"          # smallest slot width first
        assert acts[0].slots == 4             # doubles, capped at max
        # resize cooldown paces the next widening
        assert plan(hot, pol, st, now=5.0) == []
        assert st.cooldown_skips == 1
        # every server at the width ceiling: clamp, not resize
        wide = _snap(_row("a", "h:1", occupied=8, slots=8),
                     _row("b", "h:2", occupied=8, slots=8))
        assert plan(wide, pol, st, now=20.0) == []
        assert st.envelope_clamps == 1

    def test_one_action_in_flight_per_server(self):
        pol = _policy(down_streak=1, cooldown_down_s=0.0)
        st = ControllerState()
        st.inflight["a"] = RESIZE             # e.g. a resize in flight
        calm = _snap(_row("a", "h:1", occupied=0),
                     _row("b", "h:2", occupied=1))
        acts = plan(calm, pol, st, now=0.0)
        assert acts[0].target == "b"          # a is skipped, loudly
        assert st.inflight_skips == 1

    def test_inflight_spawn_counts_toward_fleet_size(self):
        pol = _policy(up_streak=1, max_servers=2)
        st = ControllerState()
        st.inflight["!spawn:1"] = SCALE_UP
        hot = _snap(_row("a", "h:1", occupied=4))
        # n_eff = 1 + 1 = max_servers: clamp instead of a runaway spawn
        assert plan(hot, pol, st, now=0.0) == []
        assert st.envelope_clamps == 1

    def test_stale_rows_excluded_from_pressure_and_targets(self):
        pol = _policy(up_streak=1, down_streak=1, cooldown_down_s=0.0)
        # a stale saturated row creates no scale-up pressure
        st = ControllerState()
        assert plan(_snap(_row("a", "h:1", occupied=4, stale=True),
                          _row("b", "h:2", occupied=0), headroom=4),
                    pol, st, now=0.0) == [] or True
        # and a stale row is never picked as the drain target
        st = ControllerState()
        calm = _snap(_row("a", "h:1", occupied=0, stale=True),
                     _row("b", "h:2", occupied=0),
                     _row("c", "h:3", occupied=1))
        acts = plan(calm, pol, st, now=0.0)
        assert acts and acts[0].target == "b"

    def test_draining_rows_never_picked(self):
        pol = _policy(down_streak=1, cooldown_down_s=0.0)
        st = ControllerState()
        calm = _snap(_row("a", "h:1", occupied=0, draining=True),
                     _row("b", "h:2", occupied=1),
                     _row("c", "h:3", occupied=2))
        acts = plan(calm, pol, st, now=0.0)
        assert acts and acts[0].target == "b"

    def test_at_most_one_action_per_tick(self):
        pol = _policy(up_streak=1, min_servers=3)
        st = ControllerState()
        acts = plan(_snap(_row("a", "h:1", occupied=4)), pol, st,
                    now=0.0)
        assert len(acts) == 1

    def test_empty_fleet_steers_to_floor(self):
        pol = _policy(min_servers=1)
        st = ControllerState()
        acts = plan({"servers": [], "rollup": {}}, pol, st, now=0.0)
        assert [a.kind for a in acts] == [SCALE_UP]


class TestPredictivePath:
    def _trained(self, min_samples=4):
        # exact surface: ttft = 10 + 100*occ + 2*n + 40*occ*n
        m = PerfModel(min_samples=min_samples)
        pts = [(o, n) for o in (0.1, 0.4, 0.7, 0.9) for n in (1, 2, 3)]
        for o, n in pts:
            m.add_sample(o, n, 100.0 * o * n,
                         10 + 100 * o + 2 * n + 40 * o * n)
        return m

    def test_reactive_fallback_below_min_samples(self):
        pol = _policy(up_streak=1, ttft_slo_ms=50.0,
                      predict_min_samples=8)
        m = PerfModel(min_samples=8)
        for i in range(7):                       # one short of k
            m.add_sample(0.1 * i, 1, 10.0, 500.0)
        assert not m.ready
        st = ControllerState()
        # mild load, no reactive trigger: with the model not ready the
        # predictive path must NOT fire — no action at all
        mild = _snap(_row("a", "h:1", occupied=2, waiting=4))
        assert plan(mild, pol, st, now=0.0, model=m) == []
        assert st.predictive_decisions == 0

    def test_predictive_fires_on_projected_burn(self):
        pol = _policy(up_streak=1, ttft_slo_ms=50.0)
        m = self._trained()
        assert m.ready
        st = ControllerState()
        # occupied 2/4 + 2 waiting -> demand 1.0 at n=1:
        # projected ttft = 10+100+2+40 = 152ms >= 50ms slo
        mild = _snap(_row("a", "h:1", occupied=2, waiting=2))
        acts = plan(mild, pol, st, now=0.0, model=m)
        assert [a.kind for a in acts] == [SCALE_UP]
        assert acts[0].predictive and "projected ttft" in acts[0].reason
        assert st.predictive_decisions == 1 and st.reactive_decisions == 0

    def test_predictive_quiet_when_projection_meets_slo(self):
        pol = _policy(up_streak=1, ttft_slo_ms=500.0)
        m = self._trained()
        st = ControllerState()
        mild = _snap(_row("a", "h:1", occupied=2, waiting=2))
        assert plan(mild, pol, st, now=0.0, model=m) == []

    def test_predictive_disabled_without_slo(self):
        pol = _policy(up_streak=1, ttft_slo_ms=0.0)
        m = self._trained()
        st = ControllerState()
        mild = _snap(_row("a", "h:1", occupied=2, waiting=2))
        assert plan(mild, pol, st, now=0.0, model=m) == []

    def test_reactive_trigger_outranks_predictive(self):
        pol = _policy(up_streak=1, ttft_slo_ms=50.0)
        m = self._trained()
        st = ControllerState()
        hot = _snap(_row("a", "h:1", occupied=4, waiting=2))
        acts = plan(hot, pol, st, now=0.0, model=m)
        assert acts and not acts[0].predictive
        assert st.reactive_decisions == 1


# ---------------------------------------------------------------------------
# PerfModel fits
# ---------------------------------------------------------------------------
class TestPerfModel:
    def test_exact_recovery_of_linear_surface(self):
        m = PerfModel(min_samples=4)
        for o in (0.2, 0.5, 0.8):
            for n in (1.0, 2.0, 4.0):
                m.add_sample(o, n, 50 * n - 30 * o, 20 + 200 * o + 5 * n)
        assert m.ready
        for o, n in ((0.3, 2.0), (0.9, 3.0)):
            assert m.predict_ttft_ms(o, n) == pytest.approx(
                20 + 200 * o + 5 * n, rel=1e-6)
            assert m.predict_tokens_per_s(o, n) == pytest.approx(
                50 * n - 30 * o, rel=1e-6)

    def test_ready_gate_needs_occupancy_spread(self):
        m = PerfModel(min_samples=3)
        for _ in range(6):
            m.add_sample(0.5, 1, 10.0, 100.0)   # one occupancy only
        assert not m.ready
        m.add_sample(0.9, 1, 12.0, 150.0)
        assert m.ready

    def test_zero_ttft_rows_feed_throughput_not_latency(self):
        m = PerfModel(min_samples=3)
        for o in (0.1, 0.5, 0.9):
            m.add_sample(o, 1, 100 * o, 0.0)    # no latency signal
        assert not m.ready                       # ttft fit starved
        assert m.predict_tokens_per_s(0.5, 1) == pytest.approx(
            50.0, rel=1e-6)

    def test_predictions_clamped_non_negative(self):
        m = PerfModel(min_samples=2)
        m.add_sample(0.1, 1, 1.0, 1.0)
        m.add_sample(0.9, 1, 0.5, 0.5)
        assert m.predict_ttft_ms(-50.0, 1) >= 0.0
        assert m.predict_tokens_per_s(-50.0, 1) >= 0.0

    def test_bench_rows_feed_the_model(self):
        m = PerfModel(min_samples=2)
        assert m.feed_bench_row({"slots": 4, "occupied": 2,
                                 "tokens_per_s": 40.0,
                                 "ttft_p95_ms": 80.0, "servers": 2})
        assert m.feed_bench_row({"occupancy": 0.9, "ttft_p95_ms": 120.0})
        assert not m.feed_bench_row({"tokens_per_s": "nan?"})  # no occ
        assert m.bench_rows == 2 and len(m) == 2
        assert m.ready

    def test_sample_window_bounded(self):
        m = PerfModel(min_samples=2)
        for i in range(PerfModel.MAX_SAMPLES + 50):
            m.add_sample(i % 7 / 7.0, 1, 1.0, 1.0)
        assert len(m) == PerfModel.MAX_SAMPLES


# ---------------------------------------------------------------------------
# FleetController: tick/reap/dispatch accounting (fake clock, fake fleet)
# ---------------------------------------------------------------------------
class _FakeObservatory:
    topic = "fake"

    def __init__(self):
        self.snap = {"servers": [], "rollup": {}}

    def snapshot(self):
        return {"servers": list(self.snap["servers"]),
                "rollup": dict(self.snap["rollup"])}


class _FailingActuator(NullActuator):
    def spawn(self, epoch=0):
        t = ActionTicket()
        self.calls.append((SCALE_UP, "", 0))
        t.resolve(False, "quota exceeded")
        return t


class _RaisingActuator(NullActuator):
    def spawn(self, epoch=0):
        raise RuntimeError("deploy plane down")


class TestFleetController:
    def _ctrl(self, actuator=None, **polkw):
        t = [0.0]
        obs = _FakeObservatory()
        pol = _policy(**polkw) if polkw else _policy()
        ctrl = FleetController(obs, actuator or NullActuator(),
                               policy=pol, clock=lambda: t[0])
        return t, obs, ctrl

    def test_tick_dispatches_and_reaps(self):
        t, obs, ctrl = self._ctrl(up_streak=1)
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        obs.snap["rollup"] = {"slot_headroom": 0}
        acts = ctrl.tick()
        assert [a.kind for a in acts] == [SCALE_UP]
        assert ctrl.scale_ups == 1 and ctrl.ticks == 1
        assert ctrl.inflight() == {"!spawn:1": SCALE_UP}
        t[0] = 1.0
        ctrl.tick()                      # NullActuator resolved instantly
        assert ctrl.inflight() == {}
        assert ctrl.actions_failed == 0
        assert [s for _, _, s in ctrl.recent] == ["dispatched", "ok"]

    def test_failed_ticket_counts_and_logs(self):
        t, obs, ctrl = self._ctrl(actuator=_FailingActuator(),
                                  up_streak=1)
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        ctrl.tick()
        t[0] = 1.0
        ctrl.tick()
        assert ctrl.actions_failed == 1
        assert any("failed" in s for _, _, s in ctrl.recent)

    def test_raising_actuator_never_kills_the_loop(self):
        t, obs, ctrl = self._ctrl(actuator=_RaisingActuator(),
                                  up_streak=1)
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        acts = ctrl.tick()               # dispatch fails, tick survives
        assert acts and ctrl.actions_failed == 1
        assert ctrl.inflight() == {}

    def test_snapshot_carries_the_decision_block(self):
        t, obs, ctrl = self._ctrl(up_streak=1)
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        ctrl.tick()
        snap = ctrl.snapshot()
        a = snap["autoscale"]
        assert a["ticks"] == 1 and a["decisions"] == 1
        assert a["inflight"] == {"!spawn:1": SCALE_UP}
        assert a["recent"][-1]["kind"] == SCALE_UP
        assert a["model_ready"] is False

    def test_model_fed_from_fresh_rows_only(self):
        t, obs, ctrl = self._ctrl()
        obs.snap["servers"] = [
            _row("a", "h:1", occupied=2),
            _row("b", "h:2", occupied=4, stale=True),
        ]
        obs.snap["rollup"] = {"tokens_per_s": 80.0, "ttft_p95_ms": 12.0}
        ctrl.tick()
        assert len(ctrl.model) == 1
        occ, n, tps, ttft = ctrl.model._rows[0]
        assert (occ, n, tps, ttft) == (0.5, 1, 80.0, 12.0)

    def test_collector_exports_every_catalogued_metric(self):
        from nnstreamer_tpu.core.telemetry import METRICS

        t, obs, ctrl = self._ctrl(up_streak=1)
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        ctrl.tick()
        samples = ctrl._collect()
        names = {s.name for s in samples}
        want = {m for m in METRICS if m.startswith("nns.autoscale.")}
        assert names == want and len(want) == 25
        by_name = {s.name: s for s in samples}
        assert by_name["nns.autoscale.ticks"].value == 1.0
        assert by_name["nns.autoscale.scale_ups"].value == 1.0
        assert by_name["nns.autoscale.actions_inflight"].value == 1.0
        # only the per-reason frozen breakdown carries extra labels,
        # and nothing froze in this healthy-plane tick
        assert all(s.labels == {"fleet": "fake"} for s in samples)

    def test_incident_dumped_per_action(self):
        class Rec:
            def __init__(self):
                self.dumps = []

            def dump(self, reason, source, detail=None, logger=None):
                self.dumps.append((reason, source, detail))

        t = [0.0]
        obs = _FakeObservatory()
        obs.snap["servers"] = [_row("a", "h:1", occupied=4)]
        rec = Rec()
        ctrl = FleetController(obs, NullActuator(),
                               policy=_policy(up_streak=1),
                               clock=lambda: t[0], recorder=rec)
        ctrl.tick()
        assert rec.dumps and rec.dumps[0][0] == "autoscale_scale_up"
        assert rec.dumps[0][1] == "autoscale"


# ---------------------------------------------------------------------------
# Satellite: the stale tier below eviction (fake clock)
# ---------------------------------------------------------------------------
def _digest(seq=1, ttl=10.0, **kw):
    d = {"v": 1, "seq": seq, "age_s": 0.0, "interval_s": 1.0,
         "ttl_s": ttl, "draining": False, "degraded": False,
         "swap": "idle", "inflight": 0, "admitted": 0, "shed": 0,
         "tokens_per_s": 0.0}
    d.update(kw)
    return d


def _announce(digest, host="h", port=1):
    return {"host": host, "port": port, "digest": digest}


class TestStaleTier:
    def test_stale_rows_flagged_and_excluded_from_gauges(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0])
        obs.ingest("a", _announce(_digest(
            seq=1, ttl=10.0, tokens=100, admitted=5, slots=4, occupied=1,
            tokens_per_s=50.0, mem_headroom_bytes=1000,
            ttft_p95_ms=20.0), port=1))
        t[0] = 2.0
        obs.ingest("b", _announce(_digest(
            seq=1, ttl=10.0, tokens=30, admitted=2, slots=4, occupied=2,
            tokens_per_s=25.0, mem_headroom_bytes=500,
            ttft_p95_ms=40.0), port=2))
        # fresh on both: full gauges, worst-tenant ttft over fresh rows
        r = obs.rollup()
        assert r["stale"] == 0
        assert r["tokens_per_s"] == 75.0
        assert r["slot_headroom"] == 3 + 2
        assert r["mem_headroom_bytes"] == 1500
        assert r["ttft_p95_ms"] == 40.0
        # a crosses stale_fraction * ttl (0.5 * 10s): flagged, excluded
        # from gauges, still LISTED and still counted in the census and
        # the cumulative counters
        t[0] = 6.0
        rows = {r["topic"]: r for r in obs.servers()}
        assert rows["a"]["stale"] is True
        assert rows["b"]["stale"] is False
        r = obs.rollup()
        assert r["servers"] == 2 and r["stale"] == 1
        assert r["tokens_per_s"] == 25.0          # a's gauge dropped
        assert r["slot_headroom"] == 2
        assert r["mem_headroom_bytes"] == 500
        assert r["ttft_p95_ms"] == 40.0
        assert r["tokens"] == 130                  # counters stay exact
        assert r["admitted"] == 7
        # a fresh digest un-stales the row without any churn
        t[0] = 7.0
        obs.ingest("a", _announce(_digest(
            seq=2, ttl=10.0, tokens=110, admitted=6, slots=4, occupied=1,
            tokens_per_s=48.0), port=1))
        r = obs.rollup()
        assert r["stale"] == 0 and r["tokens"] == 140

    def test_stale_fraction_boundary_is_strict(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0],
                               stale_fraction=0.5)
        obs.ingest("a", _announce(_digest(seq=1, ttl=10.0)))
        t[0] = 5.0                                 # exactly at the edge
        assert obs.servers()[0]["stale"] is False
        t[0] = 5.001
        assert obs.servers()[0]["stale"] is True


# ---------------------------------------------------------------------------
# Satellite: bounded retired-server ledger
# ---------------------------------------------------------------------------
class TestRetiredLedgerBound:
    def test_eviction_preserves_aggregates_exactly_and_is_loud(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0],
                               retired_cap=2)
        for i in range(5):
            obs.ingest(f"s{i}", _announce(_digest(
                seq=1, tokens=10 * (i + 1), admitted=i + 1,
                tenants={"A": {"admitted": i + 1, "shed": 0}}), port=i))
            obs.note_tombstone(f"s{i}")
        r = obs.rollup()
        assert r["retired"] == 5
        assert r["retired_evicted"] == 3           # 5 snapshots, cap 2
        assert obs.retired_evicted == 3
        # aggregates NEVER lose precision on snapshot eviction
        assert r["tokens"] == 10 + 20 + 30 + 40 + 50
        assert r["admitted"] == 1 + 2 + 3 + 4 + 5
        assert r["tenants"] == {"A": {"admitted": 15, "shed": 0}}

    def test_unevicted_resurrection_still_reverses_exactly(self):
        t = [0.0]
        obs = FleetObservatory(topic="x", clock=lambda: t[0],
                               retired_cap=8)
        obs.ingest("a", _announce(_digest(seq=1, ttl=5.0, tokens=100)))
        t[0] = 6.0                                  # TTL-evicted
        assert obs.rollup()["tokens"] == 100
        obs.ingest("a", _announce(_digest(seq=2, ttl=5.0, tokens=120)))
        r = obs.rollup()
        assert r["tokens"] == 120                   # reversed, not 220
        assert r["retired_evicted"] == 0

    def test_default_cap_matches_module_constant(self):
        from nnstreamer_tpu.core.fleet import RETIRED_ROWS_MAX

        obs = FleetObservatory(topic="x")
        assert obs.retired_cap == RETIRED_ROWS_MAX


# ---------------------------------------------------------------------------
# fleet_top: the decision column renders
# ---------------------------------------------------------------------------
def test_fleet_top_renders_decision_column_and_stale_state():
    from tools.fleet_top import render

    snapshot = {
        "rollup": {
            "servers": 2, "stale": 1, "draining": 0, "degraded": 0,
            "retired": 0, "stale_evicted": 0, "tokens_per_s": 10.0,
            "occupancy": 0.25, "occupied": 2, "slots": 8,
            "slot_headroom": 2, "mem_headroom_bytes": 0, "inflight": 2,
            "tokens": 10, "admitted": 2, "shed": 0, "tenants": {},
            "slo_burn": {}, "ttft_p95_ms": 12.5,
        },
        "servers": [
            {"addr": "127.0.0.1:9000", "seq": 3, "seen_s": 0.1,
             "slots": 4, "occupied": 2, "tokens_per_s": 10.0},
            {"addr": "127.0.0.1:9001", "seq": 2, "seen_s": 9.0,
             "stale": True, "slots": 4, "occupied": 0},
        ],
        "autoscale": {
            "ticks": 7, "decisions": 2, "target_servers": 3,
            "inflight": {"!spawn:1": "scale_up"},
            "model_samples": 12, "model_ready": True,
            "recent": [
                {"kind": "scale_up", "target": "", "status": "ok",
                 "reason": "occupancy 0.90 >= 0.85",
                 "predictive": False},
                {"kind": "scale_down", "target": "t", "status":
                 "dispatched", "reason": "calm", "predictive": True},
            ],
        },
    }
    out = render(snapshot, "prod")
    assert "1 stale" in out
    assert "stale" in out.splitlines()[-1] or "stale" in out  # row state
    assert "autoscale: target 3 server(s)" in out
    assert "model ready (12 samples)" in out
    assert "scale_up <new> (reactive)" in out
    assert "scale_down t (predictive)" in out
    assert "ttft p95" in out and "12.5ms" in out


# ---------------------------------------------------------------------------
# Zero-loss live actuation: resize on a serving generator
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_generator_live_resize_zero_loss():
    """``request_resize`` mid-decode: the engine GOAWAY-flushes live
    streams resumably, rebuilds at the new width on the dispatch thread,
    adopts the old engine's cumulative ledger, and every migrated stream
    continues bit-identically (the resume signature excludes slot
    width)."""
    from tools.chaos_fleet import FleetHarness

    h = FleetHarness(mode="generate", gen_slots=2, gen_max_new=96,
                     gen_step_ms=3.0, base_id=10150, topic="chaosresize")
    try:
        h.start_server(0)
        clients = [h.make_gen_client(f"C{i}", timeout=120.0,
                                     busy_retries=40) for i in range(2)]
        traces = [c.push_prompt() for c in clients]
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if all(c.tokens_done(tr) >= 8
                   for c, tr in zip(clients, traces)):
                break
            time.sleep(0.005)
        pipe = h.servers[0]
        gen = pipe["gen"]
        before = h.server_gen_row(pipe)
        gen.request_resize(4)
        for c in clients:
            c.settle(timeout=120.0)
        rdeadline = time.monotonic() + 15.0
        while gen.resize_pending and time.monotonic() < rdeadline:
            time.sleep(0.01)
        for c in clients:
            c.finish()
        checks = [c.check_exact() for c in clients]
        assert sum(r["mismatched"] for r in checks) == 0
        assert sum(r["exact"] for r in checks) == 2
        row = h.server_gen_row(pipe)
        assert not gen.resize_pending
        assert int(row["gen_slots"]) == 4
        assert int(row["gen_resizes"]) == 1
        # ledger continuity: cumulative counters never went backwards
        assert row["gen_tokens"] >= before["gen_tokens"]
        assert row["gen_joins"] >= before["gen_joins"]
        # the flush really handed live streams off, and every handoff
        # was migrated (possibly straight back) exactly once
        handed = int(row.get("gen_goaway_evicted", 0))
        migrations = sum(int(c.health().get("stream_migrations", 0))
                        for c in clients)
        assert handed >= 1 and migrations == handed
        assert h.breaker_trips() == 0
    finally:
        h.stop_all()


def test_generator_resize_rejects_bad_width():
    from nnstreamer_tpu.pipeline import parse_pipeline
    from nnstreamer_tpu.pipeline.element import ElementError

    pipe = parse_pipeline(
        "appsrc name=src ! tensor_generator name=gen slots=2 "
        "custom=sim:1,vocab:101 max-new=4 ! tensor_sink name=out",
        name="resizeval")
    pipe.start()
    try:
        gen = pipe["gen"]
        with pytest.raises(ElementError):
            gen.request_resize(0)
        gen.request_resize(2)            # same width: a no-op
        assert not gen.resize_pending
        # resize needs a live slot engine (guards the unslotted path
        # and pre-start calls alike)
        pipe.stop()
        with pytest.raises(ElementError):
            gen.request_resize(4)
    finally:
        pipe.stop()


def test_resize_pending_holds_until_swap_lands():
    """``resize_pending`` is the actuation-complete signal controllers
    poll: it must stay set through the WHOLE rebuild.  (Regression: it
    used to clear at the START of the apply, so a poller could read
    the OLD width as the settled result while the swap was still in
    flight.)"""
    import threading

    from nnstreamer_tpu.pipeline import parse_pipeline

    pipe = parse_pipeline(
        "appsrc name=src ! tensor_generator name=gen slots=2 "
        "custom=sim:1,vocab:101 max-new=4 ! tensor_sink name=out",
        name="resizepend")
    pipe.start()
    try:
        gen = pipe["gen"]
        gate = threading.Event()
        entered = threading.Event()
        orig = gen._build_slot_model

        def slow_build(slots):
            entered.set()
            assert gate.wait(10.0)
            return orig(slots)

        gen._build_slot_model = slow_build
        gen.request_resize(4)
        assert entered.wait(10.0)   # dispatch thread is inside the build
        assert gen.resize_pending   # ...and the signal still holds
        gate.set()
        deadline = time.monotonic() + 10.0
        while gen.resize_pending and time.monotonic() < deadline:
            time.sleep(0.005)
        assert not gen.resize_pending
        assert int(pipe.health()["gen"]["gen_slots"]) == 4
    finally:
        pipe.stop()


# ---------------------------------------------------------------------------
# The chaos acceptance (tier-1, chaos-marked)
# ---------------------------------------------------------------------------
@pytest.mark.chaos
@pytest.mark.slow  # tier-1 budget: ~16s; partition_chaos_smoke below keeps
# a controller chaos e2e in tier-1 (plus the prefix/resume chaos smokes);
# the full burst-absorption script stays in the full suite
def test_autoscale_chaos_smoke():
    """The acceptance contract: the closed loop observatory -> plan ->
    actuator scales a generate-mode fleet up under a load ramp, absorbs
    a hot-tenant burst with the victim tenant's goodput floor held,
    and — when the operator shrinks the envelope — drains a server
    UNDER LIVE LOAD with every stream migrating bit-identically; zero
    lost/duplicated streams, zero breaker trips, exact
    observatory-vs-ledger rollups, and the ``nns.autoscale.*``
    accounting exactly matching the actuation record."""
    from tools.chaos_fleet import run_autoscale_script

    v = run_autoscale_script(servers=1, streams=4)
    assert v["ok"], v
    # the contract, spelled out
    assert v["mismatched"] == 0 and v["exact"] == v["streams"]
    assert v["scale_ups"] == 2 and v["scale_downs"] == 1
    assert v["actions_failed"] == 0
    assert v["drain"]["dropped"] == 0 and v["drain"]["drain_complete"]
    assert v["handed_off"] >= 1
    assert v["migrations"] == v["handed_off"]
    assert v["victim_goodput"] >= 0.9 * v["baseline_goodput"]
    assert v["crosscheck"]["exact"]
    assert v["accounting_ok"] and v["metrics_endpoint_ok"]
    assert v["breaker_trips"] == 0
    assert v["inflight"] == {}


@pytest.mark.chaos
def test_partition_chaos_smoke():
    """The fail-static acceptance contract: the control plane is
    killed (broker death + amnesia restart), blinded, partitioned, and
    duplicated (two live leased controllers) while a generate-mode
    fleet keeps serving — the dataplane is provably untouched (zero
    lost/duplicated tokens), zero drains land on alive-but-invisible
    servers, exactly one epoch's actions apply (stale-epoch rejects
    counted), and fleet rollups are integer-exact after heal."""
    from tools.chaos_fleet import run_partition_script

    v = run_partition_script(servers=3, streams=6, seed=0, lease_ttl=4.0)
    assert v["ok"], v
    # the contract, spelled out
    assert v["mismatched"] == 0 and v["exact"] == v["streams"]
    # exactly one leader elected; the standby was refused, not queued
    assert v["election"]["epoch1"] == 1
    assert v["election"]["standby_refusals"] >= 1
    assert v["standby_actions"] == 0
    # broker death sensed: planner froze fail-static, then reconverged
    assert v["broker_outage"]["plane_lost_sensed"]
    assert v["broker_outage"]["frozen"] >= 1
    assert "broker_disconnected" in v["broker_outage"]["frozen_reasons"]
    assert v["broker_outage"]["blind_level"] == "blind"
    assert all(n >= 1 for n in v["broker_outage"]["reconnects"].values())
    assert all(n >= 1 for n in v["broker_outage"]["reannounces"].values())
    assert v["broker_outage"]["crosscheck_exact"]
    # partition: below-quorum freeze, no drains of invisible servers
    assert "below_quorum" in v["partition"]["frozen_reasons"]
    assert v["partition"]["drains_while_invisible"] == 0
    assert v["partition"]["crosscheck_after_heal"]
    # fenced drain under the first epoch only; zero drops
    assert v["scale_down"]["dropped"] == 0
    assert v["scale_down"]["drain_complete"]
    assert all(e == v["election"]["epoch1"]
               for e in v["scale_down"]["epochs"])
    # takeover: new epoch fences the deposed leader's commands
    assert v["fencing"]["epoch2"] == 2
    assert v["fencing"]["steals"] == 1
    assert v["fencing"]["self_fences"] == 1
    assert v["fencing"]["stale_reject"]
    assert v["fencing"]["gen_stale_epoch_rejects"] >= 1
    assert v["crosscheck_final"]
    assert v["breaker_trips"] == 0
