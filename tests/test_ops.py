"""ops layer: fused preprocess / top1 / batched NMS (CPU fallback paths;
the Pallas variants compile on TPU and share the same numerics)."""

import numpy as np
import pytest

from nnstreamer_tpu.ops import batched_nms, normalize_u8, top1


class TestNormalize:
    def test_default_mobilenet_transform(self):
        x = np.array([[0, 128, 255]], np.uint8)
        y = np.asarray(normalize_u8(x, dtype=np.float32))
        np.testing.assert_allclose(y, [[-1.0, 128 * 2 / 255 - 1, 1.0]], atol=1e-6)

    def test_arbitrary_shape_and_scale(self):
        x = np.arange(2 * 3 * 5, dtype=np.uint8).reshape(2, 3, 5)
        y = np.asarray(normalize_u8(x, scale=0.5, bias=1.0, dtype=np.float32))
        np.testing.assert_allclose(y, x.astype(np.float32) * 0.5 + 1.0)


class TestTop1:
    def test_batch(self):
        logits = np.array([[0.1, 2.0, -1.0], [5.0, 0.0, 4.9]], np.float32)
        idx, val = top1(logits)
        np.testing.assert_array_equal(np.asarray(idx), [1, 0])
        np.testing.assert_allclose(np.asarray(val), [2.0, 5.0])

    def test_single_row(self):
        idx, val = top1(np.float32([0.0, 1.0]))
        assert int(idx) == 1 and float(val) == 1.0


class TestBatchedNMS:
    def test_suppresses_overlaps(self):
        boxes = np.float32([
            [0, 0, 10, 10],
            [1, 1, 11, 11],   # heavy overlap with 0, lower score
            [50, 50, 60, 60],  # disjoint
        ])
        scores = np.float32([0.9, 0.8, 0.7])
        keep = np.asarray(batched_nms(boxes, scores, iou_thr=0.5))
        np.testing.assert_array_equal(keep, [True, False, True])

    def test_batched_and_padding_mask(self):
        boxes = np.zeros((2, 4, 4), np.float32)
        boxes[0, 0] = [0, 0, 10, 10]
        boxes[0, 1] = [20, 0, 30, 10]
        scores = np.zeros((2, 4), np.float32)
        scores[0, :2] = [0.9, 0.8]
        keep = np.asarray(batched_nms(boxes, scores))
        assert keep[0, 0] and keep[0, 1]
        assert not keep[0, 2:].any() and not keep[1].any()  # padded rows

    @pytest.mark.slow  # tier-1 budget: ~21s yolov5-in-graph compile;
    # the batched-NMS kernel units above keep NMS covered
    def test_yolov5_in_graph_nms(self):
        from nnstreamer_tpu.models import build

        fn, params, _, _ = build(
            "yolov5s",
            {"dtype": "float32", "size": "64", "classes": "3", "nms": "1"},
        )
        img = np.random.default_rng(0).integers(0, 255, (64, 64, 3), np.uint8)
        pred = np.asarray(fn(params, [img])[0])
        assert np.isfinite(pred).all()
        # NMS zeroes suppressed objectness: strictly fewer positives than
        # candidates (random weights produce heavy overlap)
        assert (pred[:, 4] > 0).sum() < pred.shape[0]

    @pytest.mark.slow  # tier-1 budget: ~43s compile; the in-graph NMS
    # test keeps the fused-preprocess assertions in the fast run
    def test_mobilenet_pallas_preprocess_numerics(self):
        from nnstreamer_tpu.models import build

        img = np.random.default_rng(1).integers(0, 255, (32, 32, 3), np.uint8)
        fn1, p1, _, _ = build(
            "mobilenet_v2",
            {"dtype": "float32", "size": "32", "classes": "5", "pallas": "0"},
        )
        fn2, p2, _, _ = build(
            "mobilenet_v2",
            {"dtype": "float32", "size": "32", "classes": "5", "pallas": "1"},
        )
        np.testing.assert_allclose(
            np.asarray(fn1(p1, [img])[0]),
            np.asarray(fn2(p2, [img])[0]),
            rtol=1e-5, atol=1e-5,
        )

    def test_matches_host_reference(self):
        rng = np.random.default_rng(0)
        xy = rng.random((32, 2)) * 100
        wh = rng.random((32, 2)) * 30 + 1
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rng.random(32).astype(np.float32) + 0.01
        keep = np.asarray(batched_nms(boxes, scores, iou_thr=0.45))

        # host greedy NMS oracle
        def iou(a, b):
            x1, y1 = max(a[0], b[0]), max(a[1], b[1])
            x2, y2 = min(a[2], b[2]), min(a[3], b[3])
            inter = max(x2 - x1, 0) * max(y2 - y1, 0)
            ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
            return inter / ua if ua > 0 else 0.0

        ref = np.zeros(32, bool)
        sup = np.zeros(32, bool)
        for i in np.argsort(-scores):
            if sup[i]:
                continue
            ref[i] = True
            for j in range(32):
                if j != i and iou(boxes[i], boxes[j]) > 0.45:
                    sup[j] = True
        np.testing.assert_array_equal(keep, ref)
