"""Crash-safe in-pipeline training (ISSUE 19): the kill/resume truth
table, trainer-thread supervision, the gated-promotion loop, memory-
pressure pause, the truncated-repo-prefix e2e, the co-hosted serving
perf floor, and the `--mode train` chaos acceptance smoke."""

import json
import os
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import checkpoint as ckpt
from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.resilience import FAULTS, TransientError
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import ElementError

N, B, CLASSES = 16, 8, 4           # 2 optimizer steps per epoch
STEPS_PER_EPOCH = N // B
CFG = {
    "arch": "mnist_cnn", "arch_props": {"classes": str(CLASSES)},
    "optimizer": "adam", "learning_rate": 3e-3,
    "batch_size": B, "loss": "softmax_ce",
}


@pytest.fixture(autouse=True)
def _reset_faults():
    yield
    FAULTS.reset()


def _make_frames(n=N, seed=0):
    """Deterministic learnable banded images (class = bright band)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        label = i % CLASSES
        img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
        img[label * 5 : label * 5 + 4, :, :] += 0.8
        out.append((img, np.int32([label])))
    return out


def _write_repo(dirpath, frames, claim=None, truncate_bytes=0):
    """Flat-binary datarepo + meta (the datareposink layout), directly."""
    data_path = os.path.join(dirpath, "data.bin")
    json_path = os.path.join(dirpath, "data.json")
    blob = b"".join(img.tobytes() + lab.tobytes() for img, lab in frames)
    if truncate_bytes:
        blob = blob[:-truncate_bytes]
    with open(data_path, "wb") as f:
        f.write(blob)
    sample_size = frames[0][0].nbytes + frames[0][1].nbytes
    with open(json_path, "w") as f:
        json.dump({
            "tensors": ["float32:1:28:28", "int32:1"],  # innermost-first dims
            "total_samples": claim or len(frames),
            "sample_size": sample_size,
        }, f)
    return data_path, json_path


def _templates():
    import jax
    import optax

    from nnstreamer_tpu import models as zoo

    fn, params, _, _ = zoo.build("mnist_cnn", {"classes": str(CLASSES)})
    opt = jax.jit(optax.adam(CFG["learning_rate"]).init)(params)
    return fn, params, opt


# ---------------------------------------------------------------------------
# Kill/resume truth table (backend grain): fault BEFORE the checkpoint
# write, INSIDE the torn-save gap, and on a train step AFTER a durable
# checkpoint — resume must land on the newest durable step, retrain
# nothing, and end bit-identical to an uninterrupted control run.
# ---------------------------------------------------------------------------
class TestKillResumeTruthTable:
    EPOCHS = 2

    def _run(self, ck_dir, frames, resume=False):
        from nnstreamer_tpu.trainer.jax_trainer import JaxTrainer

        tr = JaxTrainer()
        tr.create({
            "model-config": json.dumps(CFG), "num-inputs": 1,
            "num-labels": 1, "num-training-samples": N,
            "num-validation-samples": 0, "epochs": self.EPOCHS,
            "checkpoint-path": ck_dir, "checkpoint-interval": 1,
            "checkpoint-keep": 0, "resume": resume,
        })
        tr.start()
        for ep in range(self.EPOCHS):
            for i in range(N):
                fr = TensorFrame([frames[i][0], frames[i][1]])
                fr.meta["epoch"] = ep
                fr.meta["sample_index"] = i
                tr.push_data(fr)
        tr.end_of_data()
        tr._thread.join(timeout=300)
        return tr

    @pytest.fixture(scope="class")
    def control(self, tmp_path_factory):
        import jax

        frames = _make_frames()
        ck_dir = str(tmp_path_factory.mktemp("ctl") / "ck")
        tr = self._run(ck_dir, frames)
        assert tr.error is None and ckpt.latest_step(ck_dir) == self.EPOCHS
        _, params, opt = _templates()
        tpl = {"params": params, "opt_state": opt}
        leaves = jax.tree_util.tree_leaves(
            ckpt.restore_state(ck_dir, self.EPOCHS, tpl))
        return frames, tpl, leaves

    # (site, arm kwargs, durable step after the kill, samples skipped on
    # the resume replay)
    ROWS = [
        ("trainer.step", {"after": STEPS_PER_EPOCH}, 1, N),
        ("trainer.checkpoint", {}, None, 0),
        ("trainer.checkpoint.commit", {}, None, 0),
    ]

    @pytest.mark.parametrize("site,arm,durable,skipped",
                             ROWS, ids=[r[0] for r in ROWS])
    def test_kill_then_resume_bit_identical(
            self, tmp_path, control, site, arm, durable, skipped):
        import jax

        frames, tpl, control_leaves = control
        ck_dir = str(tmp_path / "ck")
        FAULTS.arm(site, exc=RuntimeError(f"injected kill at {site}"),
                   times=1, **arm)
        killed = self._run(ck_dir, frames)
        FAULTS.reset()
        assert killed.error is not None
        assert ckpt.latest_step(ck_dir) == durable
        if site == "trainer.checkpoint.commit":
            # the torn-save gap: orbax data exists, marker doesn't —
            # invisible to latest_step, overwritten by the resume run
            assert os.path.isdir(os.path.join(ck_dir, "step_1"))

        resumed = self._run(ck_dir, frames, resume=True)
        assert resumed.error is None
        assert resumed.status.epoch_count == self.EPOCHS
        assert resumed.resumes == (1 if durable is not None else 0)
        assert resumed.replay_skipped == skipped
        assert resumed.gap_samples == 0
        # the (epoch, sample_index) ledger holds no duplicates: zero
        # samples retrained
        assert len(resumed.trained_log) == len(set(resumed.trained_log))
        assert ckpt.latest_step(ck_dir) == self.EPOCHS
        leaves = jax.tree_util.tree_leaves(
            ckpt.restore_state(ck_dir, self.EPOCHS, tpl))
        assert len(leaves) == len(control_leaves)
        for a, b in zip(leaves, control_leaves):
            assert np.array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# Supervision: a dead training thread must surface on a QUIET stream
# (watchdog sweep), and error-policy=restart must revive the backend
# mid-stream with checkpoint resume + epoch-boundary realignment.
# ---------------------------------------------------------------------------
class TestTrainerSupervision:
    def _push_epoch(self, src, frames, ep, n=N, sleep=0.0):
        for i in range(n):
            fr = TensorFrame([frames[i][0], frames[i][1]])
            fr.meta["epoch"] = ep
            fr.meta["sample_index"] = i
            src.push(fr)
            if sleep:
                time.sleep(sleep)

    def test_quiet_stream_death_surfaces(self, tmp_path):
        """A trainer that dies with no further frames arriving must not
        hang until EOS: the sweep routes the error through fail-stop
        within seconds and wait() raises."""
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_trainer name=train framework=jax "
            f"model-config={cfg_path} num-inputs=1 num-labels=1 "
            f"num-training-samples={N} epochs=3 ! tensor_sink name=out"
        )
        pipe.start()
        frames = _make_frames()
        FAULTS.arm("trainer.step", exc=RuntimeError("chaos: quiet death"),
                   times=1)
        self._push_epoch(pipe["src"], frames, 0)
        # no EOS, no more frames: only the sweeper can surface this
        t0 = time.monotonic()
        with pytest.raises(ElementError, match="trainer failed"):
            pipe.wait(timeout=60)
        assert time.monotonic() - t0 < 30
        assert pipe.health()["train"]["state"] == "failed"
        assert pipe.health()["train"]["train_alive"] == 0
        pipe.stop()

    def test_restart_policy_revives_and_realigns(self, tmp_path):
        """error-policy=restart: the revived backend resumes from the
        durable checkpoint, drops the un-resumable partial epoch from
        the live stream (counted as gap), and completes the run."""
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        ck_dir = str(tmp_path / "ck")
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_trainer name=train framework=jax "
            f"model-config={cfg_path} num-inputs=1 num-labels=1 "
            f"num-training-samples={N} epochs=3 checkpoint-path={ck_dir} "
            "checkpoint-interval=1 error-policy=restart max-restarts=3 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        frames = _make_frames()
        train = pipe["train"]
        self._push_epoch(pipe["src"], frames, 0)
        deadline = time.monotonic() + 120
        while ckpt.latest_step(ck_dir) != 1:
            assert time.monotonic() < deadline, "epoch-1 checkpoint missing"
            time.sleep(0.05)
        # kill the NEXT optimizer step (mid-epoch-2, checkpoint durable)
        FAULTS.arm("trainer.step", exc=TransientError("chaos: preempted"),
                   times=1)
        self._push_epoch(pipe["src"], frames, 1, sleep=0.01)
        deadline = time.monotonic() + 60
        while train.health_info()["train_restarts"] < 1:
            assert time.monotonic() < deadline, "supervisor never revived"
            time.sleep(0.05)
        FAULTS.reset()
        # the partial epoch is gone from the live stream: supply enough
        # fresh epochs for the realign to finish the configured 3
        for ep in (2, 3, 4):
            self._push_epoch(pipe["src"], frames, ep)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=300)
        h = train.health_info()
        assert h["train_restarts"] == 1
        assert h["train_resumes"] == 1
        assert h["train_epochs"] == 3
        assert h["train_gap_samples"] >= 1  # realign is counted, never silent
        assert not pipe.errors
        pipe.stop()


# ---------------------------------------------------------------------------
# Starvation-free co-hosting: the memory watermark pauses training
# (resumable, counted) and training finishes with zero sample loss.
# ---------------------------------------------------------------------------
class TestPressurePause:
    def test_watermark_pauses_and_resumes(self, tmp_path):
        frames = _make_frames()
        data_path, json_path = _write_repo(str(tmp_path), frames)
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        pressure = {"on": True}
        pipe = parse_pipeline(
            f"datareposrc location={data_path} json={json_path} epochs=2 ! "
            f"tensor_trainer name=train framework=jax model-config={cfg_path} "
            f"num-inputs=1 num-labels=1 num-training-samples={N} epochs=2 "
            f"checkpoint-path={tmp_path / 'ck'} ! tensor_sink name=out"
        )
        pipe.enable_memory_monitor(
            high=0.90, low=0.75, sustain_s=0.0, min_poll_s=0.05,
            sample=lambda: ((95, 100, 0) if pressure["on"] else (10, 100, 0)),
        )
        pipe.start()
        train = pipe["train"]
        deadline = time.monotonic() + 60
        while not train.health_info()["train_paused"]:
            assert time.monotonic() < deadline, "pressure never paused training"
            time.sleep(0.02)
        h = train.health_info()
        assert h["train_pauses"] == 1
        frozen = h["train_steps"]
        time.sleep(0.3)  # paused means FROZEN, not slow
        assert train.health_info()["train_steps"] == frozen
        pressure["on"] = False
        pipe.wait(timeout=300)
        h = train.health_info()
        assert h["train_paused"] == 0
        assert h["train_epochs"] == 2
        assert h["train_samples"] == 2 * N  # resumable pause: zero loss
        assert h["train_pauses"] == 1
        pipe.stop()


# ---------------------------------------------------------------------------
# The promotion gate: first candidate promotes through the staged hot
# swap, a regressed candidate is refused, a promotion failure (fault
# site) degrades without killing serving, and the gate recovers.
# ---------------------------------------------------------------------------
class TestValidatorGate:
    def test_gate_promote_refuse_recover(self, tmp_path):
        import jax
        from flax import serialization

        from nnstreamer_tpu.core.checkpoint import atomic_write_bytes
        from nnstreamer_tpu.trainer.jax_trainer import make_loss_fn

        frames = _make_frames(n=N + 8)
        data_path, json_path = _write_repo(str(tmp_path), frames)
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        fn, params, opt = _templates()
        # two candidates with deterministically DIFFERENT held-out loss:
        # rank them with the gate's own objective and plant better first
        shifted = jax.tree_util.tree_map(lambda a: a + 0.5, params)
        xs = [np.stack([f[0] for f in frames[N:]])]
        ys = [np.stack([f[1] for f in frames[N:]])]
        loss_fn = jax.jit(make_loss_fn(fn, "softmax_ce"))
        cands = sorted(
            (params, shifted), key=lambda p: float(loss_fn(p, xs, ys)[0]))
        better, worse = cands
        base_path = str(tmp_path / "base.msgpack")
        atomic_write_bytes(base_path, serialization.to_bytes(params))
        ck_dir = str(tmp_path / "ck")
        promote_path = str(tmp_path / "promoted.msgpack")

        pipe = parse_pipeline(
            f"appsrc name=stats ! model_validator name=gate "
            f"checkpoint-path={ck_dir} model-config={cfg_path} "
            f"data-location={data_path} data-json={json_path} "
            f"holdout-start={N} metric=loss target=serve "
            f"promote-path={promote_path} ! tensor_sink name=vs "
            f"appsrc name=src ! tensor_filter name=serve framework=jax-xla "
            f"model={base_path} custom=arch:mnist_cnn,classes:{CLASSES} "
            "is-updatable=true staged-reload=true observation-window=2 "
            "rollback-error-burst=3 ! tensor_sink name=out"
        )
        pipe.start()
        gate, serve = pipe["gate"], pipe["serve"]
        stat = np.zeros(5, np.float64)

        def pump_until(cond, tag, deadline_s=120.0):
            deadline = time.monotonic() + deadline_s
            while not cond():
                assert time.monotonic() < deadline, tag
                pipe["src"].push(frames[0][0])
                time.sleep(0.02)

        # 1. first candidate always promotes (staged swap commits, then
        #    the observation window closes on clean frames)
        ckpt.save_state(ck_dir, 1, {"params": better, "opt_state": opt})
        pipe["stats"].push(stat)
        pump_until(lambda: serve.health_info()["model_version"] == 1
                   and serve.health_info()["swap_state"] == "idle",
                   "first promotion never committed")
        assert gate.health_info()["train_promotions"] == 1

        # 2. a regressed candidate is refused; the serving model stays
        ckpt.save_state(ck_dir, 2, {"params": worse, "opt_state": opt})
        pipe["stats"].push(stat)
        pump_until(lambda: gate.health_info()["train_promotions_refused"] == 1,
                   "regression never refused")
        h = gate.health_info()
        assert h["train_promotions"] == 1 and h["train_validations"] == 2
        assert serve.health_info()["model_version"] == 1

        # 3. promotion failure (fault site): counted, serving untouched,
        #    the pipeline stays alive
        ckpt.save_state(ck_dir, 3, {"params": better, "opt_state": opt})
        FAULTS.arm("trainer.promote",
                   exc=RuntimeError("chaos: export refused"), times=1)
        pipe["stats"].push(stat)
        pump_until(lambda: gate.health_info()["train_promote_failures"] == 1,
                   "promotion failure never counted")
        FAULTS.reset()
        assert serve.health_info()["model_version"] == 1
        assert not pipe.errors

        # 4. the gate recovers: the next candidate promotes cleanly
        ckpt.save_state(ck_dir, 4, {"params": better, "opt_state": opt})
        pipe["stats"].push(stat)
        pump_until(lambda: serve.health_info()["model_version"] == 2
                   and serve.health_info()["swap_state"] == "idle",
                   "gate did not recover after a promote failure")
        assert gate.health_info()["train_promotions"] == 2
        assert serve.health_info()["rollbacks"] == 0
        pipe["src"].end_of_stream()
        pipe["stats"].end_of_stream()
        pipe.wait(timeout=60)
        pipe.stop()


# ---------------------------------------------------------------------------
# Truncated-repo prefix -> trainer e2e: a killed repo writer leaves a
# partial tail; training runs on the complete prefix, loudly counted.
# ---------------------------------------------------------------------------
class TestTruncatedRepoTraining:
    def test_trains_on_complete_prefix(self, tmp_path):
        frames = _make_frames(n=24)
        # claim 24 samples, end the file mid-sample-17
        data_path, json_path = _write_repo(
            str(tmp_path), frames, claim=24,
            truncate_bytes=7 * (28 * 28 * 4 + 4) + 100)
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        pipe = parse_pipeline(
            f"datareposrc name=repo location={data_path} json={json_path} "
            f"epochs=1 ! "
            f"tensor_trainer name=train framework=jax model-config={cfg_path} "
            f"num-inputs=1 num-labels=1 num-training-samples={N} epochs=1 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        pipe.wait(timeout=300)
        assert pipe.health()["repo"]["truncated_samples"] == 8
        h = pipe["train"].health_info()
        assert h["train_epochs"] == 1
        assert h["train_samples"] == N  # the complete 16-sample prefix
        assert not pipe.errors
        pipe.stop()


# ---------------------------------------------------------------------------
# Co-hosted serving floor (async-sim proxy): training in the same
# pipeline graph must not starve serving below 0.9x of serving-alone.
# ---------------------------------------------------------------------------
@pytest.mark.perf
class TestCoHostedServingFloor:
    SERVE = (
        "appsrc name=src max-buffers=512 ! "
        "tensor_filter name=serve framework=async-sim custom=compute_ms:5 "
        "max-batch=8 dispatch-depth=4 ! tensor_sink name=out max-stored=1"
    )

    def _serving_fps(self, pipe, n_frames=400, reps=3):
        """Device-bound throughput on the async dispatch window: the
        5ms-per-batch simulated device service dominates, so the ratio
        measures co-hosting interference on the serving path, not host
        noise.  Best-of-reps damps scheduler jitter."""
        src, sink = pipe["src"], pipe["out"]
        got = {"n": 0}

        def materialize(f):
            np.asarray(f.tensors[0])  # block until device-side completion
            got["n"] += 1

        sink.connect_new_data(materialize)
        frame = np.zeros((64,), np.float32)
        best = 0.0
        for _ in range(reps):
            got["n"] = 0
            t0 = time.perf_counter()
            for _ in range(n_frames):
                src.push(frame)
            while got["n"] < n_frames:
                assert time.perf_counter() - t0 < 60, (
                    f"frames lost: {got['n']}/{n_frames}")
                time.sleep(0.001)
            best = max(best, n_frames / (time.perf_counter() - t0))
        return best

    def test_cohosted_floor(self, tmp_path):
        alone = parse_pipeline(self.SERVE, name="alone")
        alone.start()
        fps_alone = self._serving_fps(alone)
        alone.stop()

        frames = _make_frames()
        data_path, json_path = _write_repo(str(tmp_path), frames)
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(CFG))
        co = parse_pipeline(
            f"datareposrc location={data_path} json={json_path} epochs=500 ! "
            f"tensor_trainer name=train framework=jax model-config={cfg_path} "
            f"num-inputs=1 num-labels=1 num-training-samples={N} epochs=500 ! "
            "tensor_sink name=tsink " + self.SERVE,
            name="cohosted",
        )
        co.start()
        train = co["train"]
        # past BOTH jit compiles (train step + epoch-boundary eval) and
        # into steady state before measuring the co-hosted floor
        deadline = time.monotonic() + 120
        while train.health_info()["train_steps"] < 10 * STEPS_PER_EPOCH:
            assert time.monotonic() < deadline, "training never reached steady state"
            time.sleep(0.05)
        steps_before = train.health_info()["train_steps"]
        fps_co = self._serving_fps(co)
        h = train.health_info()
        # training genuinely ran through the measurement window...
        assert h["train_alive"] == 1 and h["train_steps"] > steps_before
        co.stop()
        # ...and serving held the floor (the ISSUE-19 acceptance pin)
        assert fps_co >= 0.9 * fps_alone, (
            f"co-hosted serving regressed: {fps_co:.0f} fps vs "
            f"{fps_alone:.0f} alone ({fps_co / fps_alone:.2f}x < 0.9x)"
        )


# ---------------------------------------------------------------------------
# The continuous-learning chaos e2e (acceptance): kill mid-epoch ->
# bit-identical resume; refuse a regression; roll back a bad promotion
# with zero frame loss; pressure-pause while co-hosted serving lives.
# ---------------------------------------------------------------------------
@pytest.mark.chaos
def test_chaos_train_script():
    from tools.chaos_fleet import run_train_script

    v = run_train_script(seed=0)
    assert v["ok"], v["checks"]
    assert v["resume"]["params_bit_identical"]
    assert v["resume"]["replay_skipped"] == 32
    assert v["refusal"]["refused"] == 1
    assert v["rollback"]["rollbacks"] == 1
    assert v["rollback"]["served"] == v["rollback"]["pushed"]
    assert v["pressure"]["pauses"] == 1
