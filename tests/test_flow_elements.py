"""Flow/composition element tests: transform, mux/demux, merge/split,
aggregator, if, crop, rate, repo, sparse, debug.

Modeled on the reference SSAT suites (tests/nnstreamer_converter, _mux,
_demux, _if, _rate, _repo, ...) as in-process pipelines with appsrc.
"""

import numpy as np
import pytest

from nnstreamer_tpu.elements.flow import register_if_custom, unregister_if_custom
from nnstreamer_tpu.elements.repo import reset_repo
from nnstreamer_tpu.pipeline import ElementError, parse_pipeline


def run_appsrc(text, frames, timeout=15, src="src", sink="out"):
    pipe = parse_pipeline(text)
    pipe.start()
    for f in frames:
        pipe[src].push(f)
    pipe[src].end_of_stream()
    pipe.wait(timeout=timeout)
    pipe.stop()
    return pipe


class TestTransform:
    def test_typecast(self):
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=typecast option=float32 ! tensor_sink name=out",
            [np.array([1, 2], np.uint8)],
        )
        assert pipe["out"].frames[0].tensors[0].dtype == np.float32

    def test_arithmetic_chain(self):
        # the canonical MobileNet preprocess: cast + scale to [-1, 1]
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=arithmetic "
            "option=typecast:float32,add:-127.5,div:127.5 ! tensor_sink name=out",
            [np.array([0, 127.5, 255], np.float32)],
        )
        np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], [-1, 0, 1])

    def test_arithmetic_per_channel(self):
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=arithmetic option=add:1|10|100 "
            "! tensor_sink name=out",
            [np.zeros((2, 3), np.float32)],
        )
        np.testing.assert_allclose(
            pipe["out"].frames[0].tensors[0], [[1, 10, 100], [1, 10, 100]]
        )

    def test_transpose_reference_dialect(self):
        # ref "1:0:2:3" swaps the two innermost dims = numpy last two axes
        arr = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=transpose option=1:0:2:3 ! "
            "tensor_sink name=out",
            [arr],
        )
        np.testing.assert_array_equal(
            pipe["out"].frames[0].tensors[0], arr.transpose(0, 1, 3, 2)
        )

    def test_dimchg(self):
        # ref "0:2": move innermost dim to position 2 — NHWC -> NCHW-ish
        arr = np.zeros((2, 4, 5, 3), np.float32)
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=dimchg option=0:2 ! tensor_sink name=out",
            [arr],
        )
        assert pipe["out"].frames[0].tensors[0].shape == (2, 3, 4, 5)

    def test_stand(self):
        arr = np.array([1, 2, 3, 4], np.float32)
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=stand option=default ! tensor_sink name=out",
            [arr],
        )
        out = pipe["out"].frames[0].tensors[0]
        assert abs(out.mean()) < 1e-5 and abs(out.std() - 1) < 1e-3

    def test_clamp(self):
        pipe = run_appsrc(
            "appsrc name=src ! tensor_transform mode=clamp option=0:1 ! tensor_sink name=out",
            [np.array([-5, 0.5, 7], np.float32)],
        )
        np.testing.assert_allclose(pipe["out"].frames[0].tensors[0], [0, 0.5, 1])

    def test_bad_mode_n(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_transform mode=nope ! tensor_sink name=out"
        )
        with pytest.raises(ElementError, match="unknown transform mode"):
            pipe.start()
        pipe.stop()

    def test_device_arrays_stay_on_device(self):
        import jax
        import jax.numpy as jnp

        pipe = parse_pipeline(
            "appsrc name=src ! tensor_transform mode=arithmetic option=mul:2 ! "
            "tensor_sink name=out to-host=false"
        )
        pipe.start()
        pipe["src"].push(jnp.ones((4,), jnp.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=10)
        pipe.stop()
        assert isinstance(pipe["out"].frames[0].tensors[0], jax.Array)


class TestMuxDemux:
    def test_mux_combines(self):
        pipe = parse_pipeline(
            "appsrc name=a ! mux.  appsrc name=b ! mux.  "
            "tensor_mux name=mux ! tensor_sink name=out"
        )
        pipe.start()
        pipe["a"].push(np.int32([1]), pts=0.0)
        pipe["b"].push(np.int32([2]), pts=0.0)
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert [int(t[0]) for t in f.tensors] == [1, 2]

    def test_demux_tensorpick(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_demux name=d tensorpick=1,0 "
            "d. ! tensor_sink name=o1  d. ! tensor_sink name=o2"
        )
        pipe.start()
        pipe["src"].push([np.int32([10]), np.int32([20])])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert int(pipe["o1"].frames[0].tensors[0][0]) == 20  # pick 1 first
        assert int(pipe["o2"].frames[0].tensors[0][0]) == 10

    def test_merge_concat_dim(self):
        pipe = parse_pipeline(
            "appsrc name=a ! m.  appsrc name=b ! m.  "
            "tensor_merge name=m mode=linear option=0 ! tensor_sink name=out"
        )
        pipe.start()
        pipe["a"].push(np.ones((2, 3), np.float32))
        pipe["b"].push(np.zeros((2, 2), np.float32))
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        # ref dim 0 = numpy last axis: (2,3)+(2,2) -> (2,5)
        assert pipe["out"].frames[0].tensors[0].shape == (2, 5)

    def test_split_sizes(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_split name=s tensorseg=3,2 option=0 "
            "s. ! tensor_sink name=o1  s. ! tensor_sink name=o2"
        )
        pipe.start()
        pipe["src"].push(np.arange(5, dtype=np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        np.testing.assert_array_equal(pipe["o1"].frames[0].tensors[0], [0, 1, 2])
        np.testing.assert_array_equal(pipe["o2"].frames[0].tensors[0], [3, 4])

    def test_mux_slowest_sync(self):
        pipe = parse_pipeline(
            "appsrc name=a ! mux.  appsrc name=b ! mux.  "
            "tensor_mux name=mux sync-mode=slowest ! tensor_sink name=out"
        )
        pipe.start()
        for i, pts in enumerate([0.0, 0.1, 0.2]):
            pipe["a"].push(np.int32([i]), pts=pts)
        pipe["b"].push(np.int32([100]), pts=0.2)
        pipe["a"].end_of_stream()
        pipe["b"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert int(f.tensors[0][0]) == 2  # fast pad dropped to base 0.2


class TestAggregator:
    def test_concat_frames(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_aggregator frames-out=2 frames-dim=2 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        for i in range(4):
            pipe["src"].push(np.full((1, 4, 4), i, np.float32))  # ref dim2 = np axis0
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        frames = pipe["out"].frames
        assert len(frames) == 2
        assert frames[0].tensors[0].shape == (2, 4, 4)
        assert frames[0].tensors[0][0, 0, 0] == 0 and frames[0].tensors[0][1, 0, 0] == 1

    def test_overlapping_window(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_aggregator frames-out=2 frames-flush=1 "
            "frames-dim=1 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(3):
            pipe["src"].push(np.full((1, 2), i, np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        # windows: [0,1], [1,2] (stride 1)
        assert len(pipe["out"].frames) == 2
        np.testing.assert_array_equal(
            pipe["out"].frames[1].tensors[0], [[1, 1], [2, 2]]
        )


class TestTensorIf:
    def test_average_gt_routes(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_if compared-value=tensor_average_value "
            "compared-value-option=0 supplied-value=0.5 operator=gt "
            "then=passthrough else=skip ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.float32([0.9, 0.9]))  # avg .9 > .5 -> pass
        pipe["src"].push(np.float32([0.1, 0.1]))  # avg .1 -> skip
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(pipe["out"].frames) == 1
        assert pipe["out"].frames[0].meta["tensor_if"] == "then"

    def test_then_else_branches(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_if name=i compared-value=a_value "
            "compared-value-option=0,0 supplied-value=5 operator=ge "
            "then=passthrough else=passthrough "
            "i. ! tensor_sink name=t  i. ! tensor_sink name=e"
        )
        pipe.start()
        pipe["src"].push(np.float32([7]))
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(pipe["t"].frames) == 1 and len(pipe["e"].frames) == 1
        assert float(pipe["t"].frames[0].tensors[0][0]) == 7

    def test_custom_predicate(self):
        register_if_custom("always_no", lambda f: 0.0)
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_if compared-value=custom "
                "compared-value-option=always_no supplied-value=0.5 operator=gt "
                "then=passthrough else=skip ! tensor_sink name=out"
            )
            pipe.start()
            pipe["src"].push(np.float32([1.0]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            pipe.stop()
            assert len(pipe["out"].frames) == 0
        finally:
            unregister_if_custom("always_no")

    def test_tensorpick_behavior(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_if compared-value=tensor_average_value "
            "compared-value-option=0 supplied-value=0 operator=ge "
            "then=tensorpick then-option=1 else=skip ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push([np.float32([1]), np.float32([42])])
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert len(f.tensors) == 1 and float(f.tensors[0][0]) == 42


class TestCrop:
    def test_crop_regions(self):
        pipe = parse_pipeline(
            "appsrc name=raw ! c.  appsrc name=info ! c.  "
            "tensor_crop name=c ! tensor_sink name=out"
        )
        pipe.start()
        img = np.arange(64, dtype=np.uint8).reshape(8, 8)
        pipe["raw"].push(img)
        pipe["info"].push(np.int32([[1, 2, 3, 4], [0, 0, 2, 2]]))  # x,y,w,h
        pipe["raw"].end_of_stream()
        pipe["info"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        f = pipe["out"].frames[0]
        assert len(f.tensors) == 2
        np.testing.assert_array_equal(f.tensors[0], img[2:6, 1:4])
        np.testing.assert_array_equal(f.tensors[1], img[0:2, 0:2])


class TestRate:
    def test_downsample_drops(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_rate framerate=10/1 throttle=true ! "
            "tensor_sink name=out"
        )
        pipe.start()
        for i in range(30):  # 30 fps input, 1 second
            pipe["src"].push(np.int32([i]), pts=i / 30)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        n = len(pipe["out"].frames)
        assert 9 <= n <= 11  # ~10 fps out

    def test_upsample_duplicates(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_rate framerate=20/1 throttle=false ! "
            "tensor_sink name=out"
        )
        pipe.start()
        for i in range(10):  # 10 fps input, 1 second
            pipe["src"].push(np.int32([i]), pts=i / 10)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=15)
        pipe.stop()
        assert len(pipe["out"].frames) >= 18  # ~20 fps out


class TestRepo:
    def test_loop_roundtrip(self):
        reset_repo()
        # writer pipeline -> slot 7 -> reader pipeline
        w = parse_pipeline("appsrc name=src ! tensor_reposink slot-index=7")
        r = parse_pipeline("tensor_reposrc slot-index=7 ! tensor_sink name=out")
        w.start()
        r.start()
        for i in range(3):
            w["src"].push(np.int32([i]))
        w["src"].end_of_stream()
        w.wait(timeout=10)
        r.wait(timeout=10)
        w.stop()
        r.stop()
        assert [int(f.tensors[0][0]) for f in r["out"].frames] == [0, 1, 2]


class TestSparse:
    def test_enc_dec_roundtrip(self):
        dense = np.zeros((4, 4), np.float32)
        dense[1, 2] = 5.0
        dense[3, 3] = -1.0
        pipe = run_appsrc(
            "appsrc name=src ! tensor_sparse_enc ! tensor_sparse_dec ! tensor_sink name=out",
            [dense],
        )
        np.testing.assert_array_equal(pipe["out"].frames[0].tensors[0], dense)

    def test_dec_without_meta_n(self):
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_sparse_dec ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.float32([1]))
        pipe["src"].end_of_stream()
        with pytest.raises(ElementError):
            pipe.wait(timeout=10)
        pipe.stop()


class TestDebug:
    def test_passthrough_and_counts(self):
        pipe = run_appsrc(
            "appsrc name=src ! tensor_debug name=d output-method=off ! tensor_sink name=out",
            [np.float32([1]), np.float32([2])],
        )
        assert len(pipe["out"].frames) == 2
        assert pipe["d"].seen == 2


class TestLeakyQueue:
    """GstQueue leaky modes: a full queue drops frames instead of
    blocking the producer (live pipelines must not stall on a slow
    consumer); events are never dropped."""

    def _run(self, leaky, n=40):
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=64 ! "
            f"queue max-buffers=2 leaky={leaky} ! "
            "identity sleep=0.02 ! tensor_sink name=out"
        )
        pipe.start()
        for i in range(n):
            pipe["src"].push(np.int32([i]))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        pipe.stop()
        return [int(f.tensors[0][0]) for f in pipe["out"].frames]

    def test_upstream_drops_newest(self):
        got = self._run("upstream")
        assert 0 < len(got) < 40  # dropped under pressure
        assert got[0] == 0  # earliest frames survive
        assert got == sorted(got)

    def test_downstream_drops_oldest(self):
        got = self._run("downstream")
        assert 0 < len(got) < 40
        assert got[-1] == 39  # newest frame survives (oldest were dropped)
        assert got == sorted(got)

    def test_no_leak_keeps_everything(self):
        got = self._run("no")
        assert got == list(range(40))

    def test_bad_mode_rejected(self):
        pipe = parse_pipeline(
            "appsrc name=src ! queue leaky=sideways ! tensor_sink"
        )
        with pytest.raises(Exception, match="leaky"):
            pipe.start()
            pipe.wait(timeout=10)
