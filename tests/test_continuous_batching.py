"""Continuous batching: slot scheduler, paged KV cache, multiplexed
token streams (core/slots.py + models/transformer.py SlotModel +
tensor_generator slots=N).

Oracles:

* REAL model — the slotted path must be BIT-IDENTICAL per stream to the
  seed ``generate:<N>`` one-shot path and to the unslotted streaming
  path (same params seed, same sampling seed, same per-step key
  folding): continuous batching is a scheduling change, never a
  sampling change.
* SIM model — token 1 = ``sum(prompt) % vocab``, token j+1 =
  ``(31 t_j + 17) % vocab``: exact per-stream accounting and
  cross-slot-contamination checks without model cost.
"""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.core.slots import SimSlotModel, SlotEngine
from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline

PROPS = {
    "dtype": "float32", "vocab": 61, "d_model": 32, "heads": 2,
    "layers": 2, "d_ff": 64, "seq": 64, "seed": 11,
}
CUSTOM = ",".join(f"{k}:{v}" for k, v in PROPS.items())
SAMPLING = "temperature:0.8,top_k:7,gen_seed:3"


def _oneshot(prompt, n, extra=None):
    props = {**{k: str(v) for k, v in PROPS.items()}, "generate": str(n)}
    if extra:
        props.update(extra)
    fn, params, _, _ = build("transformer", props)
    return np.asarray(fn(params, [prompt])[0])[:, prompt.shape[1]:]


def sim_oracle(model: SimSlotModel, prompt, n):
    t = int(prompt.sum()) % model.vocab
    out = [t]
    for _ in range(n - 1):
        t = model.step_token(t)
        out.append(t)
    return np.asarray([out], np.int32)


def _stream_tokens(frames):
    """Concatenate one stream's chunk frames (tensor-less typed-expiry
    frames contribute nothing) after asserting chunk-meta coherence."""
    frames = sorted(frames, key=lambda f: f.meta["chunk_index"])
    assert [f.meta["chunk_index"] for f in frames] == list(
        range(len(frames)))
    assert frames[-1].meta["final"] is True
    assert all(f.meta["final"] is False for f in frames[:-1])
    parts = [np.asarray(f.tensors[0]) for f in frames if f.tensors]
    toks = (np.concatenate(parts, axis=1) if parts
            else np.zeros((1, 0), np.int32))
    assert frames[-1].meta["tokens_done"] == toks.shape[1]
    return toks


def _group_by_stream(frames):
    by_seq = {}
    for f in frames:
        by_seq.setdefault(f.meta["stream_seq"], []).append(f)
    return by_seq


# ---------------------------------------------------------------------------
# Model-level: per-slot paged cache parity (bit-identical single occupant)
# ---------------------------------------------------------------------------
class TestSlotModelParity:
    @pytest.mark.parametrize("extra", [None, {
        "temperature": "0.8", "top_k": "7", "gen_seed": "3"}],
        ids=["greedy", "sampling"])
    def test_single_occupant_bit_parity(self, rng, extra):
        """An occupant in the MIDDLE slot of a 4-wide batch, decoded in
        mixed-length scans, is bit-equal to the one-shot generate:<N>
        tokens — and the decode step compiles once per scan length."""
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import build_slot_stream

        props = {k: str(v) for k, v in PROPS.items()}
        if extra:
            props.update(extra)
        prompt = rng.integers(0, 61, (1, 7)).astype(np.int32)
        n = 13
        want = _oneshot(prompt, n, extra)
        model, params, _ = build_slot_stream(props, 4)
        cache = model.init_cache()
        slot = np.int32(2)
        cache = model.reset_slot(cache, slot)
        cache, logits = model.prefill_fn(7)(params, cache, prompt, slot)
        t1 = model.pick_first(logits)
        got = [np.asarray(t1)[:, None]]
        tok = jnp.zeros((4,), jnp.int32).at[2].set(t1[0])
        gen = jnp.zeros((4,), jnp.int32).at[2].set(1)
        active = jnp.zeros((4,), jnp.int32).at[2].set(1)
        for k in (5, 4, 3):  # mixed scan buckets, 12 decode tokens
            cache, tok, gen, toks = model.decode_fn(k)(
                params, cache, tok, gen, active)
            got.append(np.asarray(toks)[2:3, :])
        np.testing.assert_array_equal(
            np.concatenate(got, axis=1), want)
        assert model.decode_compiles == 3  # one per distinct k, no churn

    def test_chunked_prefill_token_parity(self, rng):
        """A prompt prefilled in PIECES (interleaved-join path) yields
        the same tokens as the one-pass prefill oracle."""
        import jax.numpy as jnp

        from nnstreamer_tpu.models.transformer import build_slot_stream

        props = {k: str(v) for k, v in PROPS.items()}
        prompt = rng.integers(0, 61, (1, 20)).astype(np.int32)
        n = 8
        want = _oneshot(prompt, n)
        model, params, _ = build_slot_stream(props, 2)
        cache = model.reset_slot(model.init_cache(), np.int32(0))
        logits = None
        for lo in range(0, 20, 6):  # chunks 6,6,6,2
            piece = prompt[:, lo:lo + 6]
            cache, logits = model.prefill_fn(piece.shape[1])(
                params, cache, piece, np.int32(0))
        t1 = model.pick_first(logits)
        got = [np.asarray(t1)[:, None]]
        tok = jnp.zeros((2,), jnp.int32).at[0].set(t1[0])
        gen = jnp.zeros((2,), jnp.int32).at[0].set(1)
        active = jnp.zeros((2,), jnp.int32).at[0].set(1)
        cache, tok, gen, toks = model.decode_fn(n - 1)(
            params, cache, tok, gen, active)
        got.append(np.asarray(toks)[0:1])
        np.testing.assert_array_equal(np.concatenate(got, axis=1), want)

    def test_join_touches_only_its_slot(self, rng):
        """A joining stream's reset+prefill leaves every NEIGHBOR page
        bit-untouched (the leave/join page-reuse contract)."""
        import jax

        from nnstreamer_tpu.models.transformer import build_slot_stream

        props = {k: str(v) for k, v in PROPS.items()}
        model, params, _ = build_slot_stream(props, 3)
        cache = model.init_cache()
        # occupy slot 0 with a stream so its pages are non-trivial
        p0 = rng.integers(0, 61, (1, 9)).astype(np.int32)
        cache = model.reset_slot(cache, np.int32(0))
        cache, _ = model.prefill_fn(9)(params, cache, p0, np.int32(0))
        before = [np.array(leaf)[0] for leaf in jax.tree.leaves(cache)]
        # join slot 2: reset + prefill a different prompt
        p2 = rng.integers(0, 61, (1, 5)).astype(np.int32)
        cache = model.reset_slot(cache, np.int32(2))
        cache, _ = model.prefill_fn(5)(params, cache, p2, np.int32(2))
        after = [np.array(leaf)[0] for leaf in jax.tree.leaves(cache)]
        for b, a in zip(before, after):
            np.testing.assert_array_equal(b, a)


# ---------------------------------------------------------------------------
# Engine-level: scheduling, accounting, eviction (sim model — fast)
# ---------------------------------------------------------------------------
def _mk_engine(slots=2, vocab=97, chunk=4, step_ms=0.2, **kw):
    model = SimSlotModel(slots, vocab=vocab, step_base_ms=step_ms,
                         step_per_slot_ms=0.01, prefill_ms_per_token=0.01)
    eng = SlotEngine(model, None, max_seq=1 << 30, chunk=chunk,
                     name="test", **kw)
    eng.start()
    return eng, model


def _frame(prompt, **meta):
    return TensorFrame([prompt], meta=dict(meta))


def _drain(eng, until, timeout=20.0):
    out = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out.extend(eng.pop_ready())
        if until(out):
            return out
        eng.wait_progress(0.02)
    raise TimeoutError(f"engine drain timed out with {len(out)} chunks")


class TestSlotEngine:
    def test_concurrent_streams_exact_accounting(self, rng):
        """5 streams through 2 slots: every stream's tokens equal its
        oracle (zero cross-slot contamination), exact counters."""
        eng, model = _mk_engine(slots=2)
        try:
            prompts = [
                rng.integers(0, 97, (1, 5 + i)).astype(np.int32)
                for i in range(5)
            ]
            for p in prompts:
                eng.submit(_frame(p), p, max_new=11, chunk=4)
            outs = _drain(
                eng, lambda o: sum(
                    1 for _p, f in o if f.meta["final"]) >= 5)
            by_seq = _group_by_stream([f for _pad, f in outs])
            assert len(by_seq) == 5
            matched = 0
            for frames in by_seq.values():
                toks = _stream_tokens(frames)
                assert toks.shape == (1, 11)
                for p in prompts:
                    if np.array_equal(toks, sim_oracle(model, p, 11)):
                        matched += 1
                        break
            assert matched == 5
            snap = eng.snapshot()
            assert snap["gen_joins"] == 5
            assert snap["gen_completed"] == 5
            assert snap["gen_occupied"] == 0
            assert snap["gen_tokens"] == 55
        finally:
            eng.stop()

    def test_priority_wins_free_slot(self, rng):
        """With every slot busy, a later high-priority prompt beats an
        earlier low-priority one to the next free slot (PR-8 classes
        extend to slot admission)."""
        eng, model = _mk_engine(slots=1, step_ms=1.0)
        try:
            p0 = rng.integers(0, 97, (1, 4)).astype(np.int32)
            lo = rng.integers(0, 97, (1, 4)).astype(np.int32)
            hi = rng.integers(0, 97, (1, 4)).astype(np.int32)
            eng.submit(_frame(p0), p0, max_new=24, chunk=4)
            time.sleep(0.01)
            s_lo = eng.submit(_frame(lo), lo, max_new=4, chunk=4,
                              priority=0)
            s_hi = eng.submit(_frame(hi), hi, max_new=4, chunk=4,
                              priority=3)
            _drain(eng, lambda o: sum(
                1 for _p, f in o if f.meta["final"]) >= 3)
            assert s_hi.joined_ts is not None
            assert s_lo.joined_ts is not None
            assert s_hi.joined_ts <= s_lo.joined_ts
        finally:
            eng.stop()

    def test_cancel_frees_slot_immediately(self, rng):
        eng, model = _mk_engine(slots=1, step_ms=1.0)
        try:
            p = rng.integers(0, 97, (1, 4)).astype(np.int32)
            s = eng.submit(_frame(p, client_id=42), p,
                           max_new=10_000, chunk=4)
            _drain(eng, lambda o: len(o) >= 2)  # mid-decode
            assert eng.cancel(client_id=42)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if eng.snapshot()["gen_occupied"] == 0:
                    break
                time.sleep(0.01)
            snap = eng.snapshot()
            assert snap["gen_occupied"] == 0
            assert snap["gen_cancelled"] == 1
            assert s.state == "cancelled"
            # cancellation emits nothing further; engine drains clean
            assert eng.idle() or eng.pop_ready() is not None
        finally:
            eng.stop()

    def test_deadline_eviction_typed_expiry(self, rng):
        """A stream whose PR-2 deadline passes mid-decode is EVICTED:
        final chunk carries the typed-expiry meta, partial tokens are
        preserved and exactly oracle-prefix, the slot frees."""
        eng, model = _mk_engine(slots=1, step_ms=1.0)
        try:
            p = rng.integers(0, 97, (1, 4)).astype(np.int32)
            eng.submit(_frame(p), p, max_new=10_000, chunk=4,
                       deadline_ts=eng.clock() + 0.3)
            outs = _drain(
                eng, lambda o: any(f.meta["final"] for _p, f in o))
            frames = [f for _pad, f in outs]
            toks = _stream_tokens(frames)
            last = frames[-1].meta
            assert last["evicted"] == "deadline"
            assert last["deadline_expired"] is True
            assert 0 < toks.shape[1] < 10_000
            np.testing.assert_array_equal(
                toks, sim_oracle(model, p, toks.shape[1]))
            snap = eng.snapshot()
            assert snap["gen_evicted"] == 1
            assert snap["gen_occupied"] == 0
        finally:
            eng.stop()

    def test_token_budget_pace_eviction(self, rng):
        """token-budget-s: a stream slower than its per-token pace is
        evicted with the typed expiry (reason=token_budget)."""
        eng, model = _mk_engine(slots=1, step_ms=30.0,
                                token_budget_s=0.01)
        try:
            p = rng.integers(0, 97, (1, 4)).astype(np.int32)
            eng.submit(_frame(p), p, max_new=10_000, chunk=4)
            outs = _drain(
                eng, lambda o: any(f.meta["final"] for _p, f in o),
                timeout=30.0)
            last = [f for _p, f in outs][-1].meta
            assert last["evicted"] == "token_budget"
            assert eng.snapshot()["gen_evicted"] == 1
        finally:
            eng.stop()

    def test_zero_retrace_across_churn(self, rng):
        """Streams joining and leaving NEVER retrace the decode step:
        with chunk-aligned lengths there is exactly one decode bucket,
        however many streams churn through the slots."""
        eng, model = _mk_engine(slots=3, chunk=4)
        try:
            compiles_after_first = None
            for wave in range(3):
                prompts = [
                    rng.integers(0, 97, (1, 6)).astype(np.int32)
                    for _ in range(4)
                ]
                for p in prompts:
                    eng.submit(_frame(p), p, max_new=8, chunk=4)
                _drain(eng, lambda o: sum(
                    1 for _p, f in o if f.meta["final"]) >= 4)
                if compiles_after_first is None:
                    compiles_after_first = (
                        eng.snapshot()["gen_decode_compiles"])
            snap = eng.snapshot()
            assert snap["gen_completed"] == 12
            # the k-bucket set is fixed by (chunk, max_new); churn after
            # the first wave compiles NOTHING new
            assert snap["gen_decode_compiles"] == compiles_after_first <= 2
        finally:
            eng.stop()

    def test_jit_buckets_lru_bounded(self, rng):
        """Distinct prefill chunk lengths churn past the cap: live
        buckets stay bounded (gen_jit_buckets), work stays correct."""
        eng, model = _mk_engine(slots=1, chunk=4, jit_bucket_max=3)
        try:
            lens = [3, 5, 7, 9, 11, 13]
            for ln in lens:
                p = rng.integers(0, 97, (1, ln)).astype(np.int32)
                eng.submit(_frame(p), p, max_new=4, chunk=4)
            _drain(eng, lambda o: sum(
                1 for _p, f in o if f.meta["final"]) >= len(lens))
            assert eng.snapshot()["gen_jit_buckets"] <= 2 * 3
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# Element-level: single-occupant parity through the pipeline (satellite)
# ---------------------------------------------------------------------------
def _run_pipeline_stream(prompts, n, chunk, slots, fuse=True,
                         extra_custom=""):
    custom = CUSTOM + ("," + extra_custom if extra_custom else "")
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_generator slots={slots} "
        f"custom={custom} max-new={n} chunk={chunk} ! "
        "tensor_sink name=out", fuse=fuse,
    )
    pipe.start()
    for p in prompts:
        pipe["src"].push(p)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=180)
    frames = pipe["out"].frames
    health = pipe.health()
    pipe.stop()
    gen_key = next(k for k in health if k.startswith("tensor_generator"))
    return frames, health[gen_key]


class TestSlottedElementParity:
    @pytest.mark.parametrize("fuse", [
        pytest.param(True, id="fused"),
        # tier-1 budget: ~18s second full compile; unfused slotted
        # bit-parity stays tier-1 via the prefix element-wiring [unfused]
        # pin, which drives the same unfused slotted dataplane
        pytest.param(False, marks=pytest.mark.slow, id="unfused"),
    ])
    def test_slotted_bit_identical_to_seed_paths(self, rng, fuse):
        """Slotted decode vs seed generate:<N> AND vs the unslotted
        streaming path: tokens and chunk meta bit-identical per stream,
        fused and unfused."""
        prompts = [rng.integers(0, 61, (1, 7)).astype(np.int32),
                   rng.integers(0, 61, (1, 5)).astype(np.int32)]
        n, chunk = 13, 4
        slotted, health = _run_pipeline_stream(prompts, n, chunk, slots=3,
                                               fuse=fuse)
        unslotted, _ = _run_pipeline_stream(prompts, n, chunk, slots=0,
                                            fuse=fuse)
        by_stream = _group_by_stream(slotted)
        assert len(by_stream) == 2
        want = [_oneshot(p, n) for p in prompts]
        got = []
        for frames in by_stream.values():
            toks = _stream_tokens(frames)
            # chunk sizing matches the unslotted path: chunk-aligned
            # with one tail
            sizes = [np.asarray(f.tensors[0]).shape[1]
                     for f in sorted(frames,
                                     key=lambda f: f.meta["chunk_index"])]
            assert sizes == [4, 4, 4, 1]
            got.append(toks)
        for w in want:
            assert any(np.array_equal(g, w) for g in got)
        # the unslotted frames agree too (transitive, but pin it)
        un_by = _group_by_stream(unslotted)
        un_toks = sorted(
            (_stream_tokens(f).tolist() for f in un_by.values()))
        assert un_toks == sorted(g.tolist() for g in got)
        assert health["gen_completed"] == 2
        assert health["gen_occupied"] == 0

    def test_sampling_parity_slotted(self, rng):
        """temperature/top-k sampling through shared slots stays
        bit-equal per stream to the one-shot path (per-slot key
        folding == per-step folding)."""
        prompts = [rng.integers(0, 61, (1, 4)).astype(np.int32),
                   rng.integers(0, 61, (1, 6)).astype(np.int32)]
        n = 9
        frames, _ = _run_pipeline_stream(
            prompts, n, 4, slots=2, extra_custom=SAMPLING)
        by_stream = _group_by_stream(frames)
        want = [
            _oneshot(p, n, {"temperature": "0.8", "top_k": "7",
                            "gen_seed": "3"})
            for p in prompts
        ]
        got = [_stream_tokens(f) for f in by_stream.values()]
        for w in want:
            assert any(np.array_equal(g, w) for g in got)

    def test_block_of_prompts_splits_into_streams(self, rng):
        """A pushed BLOCK of prompts becomes one slot stream per row."""
        prompts = rng.integers(0, 61, (2, 5)).astype(np.int32)
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator slots=2 custom={CUSTOM} "
            "max-new=6 chunk=4 ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push_block(prompts)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        frames = pipe["out"].frames
        pipe.stop()
        by_stream = _group_by_stream(frames)
        assert len(by_stream) == 2
        want = [_oneshot(prompts[j:j + 1], 6) for j in range(2)]
        got = [_stream_tokens(f) for f in by_stream.values()]
        for w in want:
            assert any(np.array_equal(g, w) for g in got)

    def test_overrun_fails_loud_slotted(self, rng):
        prompt = rng.integers(0, 61, (1, 60)).astype(np.int32)
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_generator slots=2 custom={CUSTOM} "
            "max-new=32 chunk=8 ! tensor_sink name=out")
        pipe.start()
        pipe["src"].push(prompt)
        pipe["src"].end_of_stream()
        with pytest.raises(Exception, match="exceeds the model's seq"):
            pipe.wait(timeout=60)
        pipe.stop()


# ---------------------------------------------------------------------------
# Serving-level: many concurrent wire streams share the slots
# ---------------------------------------------------------------------------
def _stream_client(port, ct, prompt, results, key, timeout=120,
                   name=None):
    pipe = parse_pipeline(
        f"appsrc name=src ! tensor_query_client port={port} "
        f"connect-type={ct} stream=true timeout={timeout} ! "
        "tensor_sink name=out", name=name or f"cli{key}")
    pipe.start()
    pipe["src"].push(prompt)
    pipe["src"].end_of_stream()
    try:
        pipe.wait(timeout=timeout + 30)
        results[key] = list(pipe["out"].frames)
    finally:
        pipe.stop()


class TestMultiplexedServing:
    @pytest.mark.parametrize("ct", [
        # tier-1 budget: ~15s; same multiplex contract over a second
        # transport — grpc framing stays tier-1 via the remote-stream
        # roundtrip test, so only the tcp variant runs in tier-1
        pytest.param("grpc", marks=pytest.mark.slow),
        "tcp",
    ])
    def test_concurrent_streams_share_slots_exact(self, rng, ct,
                                                  module_leak_check):
        """N concurrent InvokeStream/tcp-stream clients multiplex into
        shared slots: per-stream tokens bit-equal to the seed one-shot
        path (zero cross-slot contamination), slots provably SHARED
        (tokens-per-step EWMA > 1), zero retraces."""
        n = 10
        sid = 761 if ct == "grpc" else 762
        server = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            f"connect-type={ct} ! "
            f"tensor_generator name=gen slots=3 custom={CUSTOM} "
            f"max-new={n} chunk=3 ! "
            f"tensor_query_serversink id={sid}")
        server.start()
        port = server["ssrc"].props["port"]
        try:
            prompts = [
                rng.integers(0, 61, (1, 4 + i)).astype(np.int32)
                for i in range(3)
            ]
            results = {}
            ts = [
                threading.Thread(
                    target=_stream_client,
                    args=(port, ct, p, results, i),
                    kwargs={"name": f"{ct}cli{i}"})
                for i, p in enumerate(prompts)
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=180)
            gen_health = server.health()["gen"]
        finally:
            server.stop()
        assert sorted(results) == [0, 1, 2]
        for i, p in enumerate(prompts):
            toks = _stream_tokens(results[i])
            np.testing.assert_array_equal(toks, _oneshot(p, n))
        assert gen_health["gen_joins"] == 3
        assert gen_health["gen_completed"] == 3
        assert gen_health["gen_occupied"] == 0
        # slots were genuinely SHARED, not serialized
        assert gen_health["gen_tokens_per_step"] > 1.0
        assert gen_health["gen_decode_compiles"] <= 4

    def test_tcp_stream_single_answer_graph(self, rng, module_leak_check):
        """A non-streaming server graph under the raw-TCP 'S' message:
        exactly one answer per request (absent final closes), parity
        with the gRPC InvokeStream contract."""
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model, unregister_jax_model)

        register_jax_model("tstream_cb", lambda p, xs: [xs[0] * 3.0], None)
        try:
            server = parse_pipeline(
                "tensor_query_serversrc name=ssrc id=763 port=0 "
                "connect-type=tcp ! "
                "tensor_filter framework=jax-xla model=tstream_cb ! "
                "tensor_query_serversink id=763")
            server.start()
            port = server["ssrc"].props["port"]
            try:
                client = parse_pipeline(
                    f"appsrc name=src ! tensor_query_client port={port} "
                    "connect-type=tcp stream=true ! tensor_sink name=out")
                client.start()
                for i in range(4):
                    client["src"].push(np.float32([i]))
                client["src"].end_of_stream()
                client.wait(timeout=60)
                vals = [float(f.tensors[0][0])
                        for f in client["out"].frames]
                client.stop()
                assert vals == [0.0, 3.0, 6.0, 9.0]
            finally:
                server.stop()
        finally:
            unregister_jax_model("tstream_cb")


# ---------------------------------------------------------------------------
# Acceptance: chaos-tolerant e2e — join, finish, kill, deadline-evict
# ---------------------------------------------------------------------------
@pytest.mark.chaos
class TestContinuousBatchingChaos:
    def test_join_kill_evict_exact_accounting(self, rng,
                                              module_leak_check):
        """The tentpole verdict: concurrent streams join shared slots,
        one finishes, one is KILLED mid-decode (client vanishes), one is
        DEADLINE-EVICTED (typed expiry with partial tokens) — exact
        per-stream token accounting against the sim oracle, zero
        cross-slot contamination, every slot freed, counters exact."""
        sim = SimSlotModel(2, vocab=997)  # oracle twin of the server's
        # ~2ms/token: a full stream takes ~8s+ — longer than BOTH the
        # 0.5s eviction budget AND the ~5s a hard client stop takes to
        # close its held stream socket (the kill must land mid-decode)
        n = 4000
        custom = ("sim:1,sim_step_ms:2.0,sim_per_slot_ms:0.05,"
                  "sim_prefill_ms:0.02,vocab:997")
        server = parse_pipeline(
            "tensor_query_serversrc name=ssrc id=764 port=0 "
            "connect-type=tcp ! "
            f"tensor_generator name=gen slots=2 custom={custom} "
            f"max-new={n} chunk=4 ! "
            "tensor_query_serversink id=764")
        server.start()
        port = server["ssrc"].props["port"]
        try:
            p_fin = rng.integers(0, 997, (1, 5)).astype(np.int32)
            p_kill = rng.integers(0, 997, (1, 6)).astype(np.int32)
            p_evict = rng.integers(0, 997, (1, 7)).astype(np.int32)
            results = {}

            # finisher: normal stream, completes its 40 tokens
            t_fin = threading.Thread(
                target=_stream_client,
                args=(port, "tcp", p_fin, results, "fin"),
                kwargs={"name": "chaos-fin"})
            t_fin.start()

            # victim: killed after >= 2 chunks (hard client stop)
            victim = parse_pipeline(
                f"appsrc name=src ! tensor_query_client port={port} "
                "connect-type=tcp stream=true timeout=60 ! "
                "tensor_sink name=out", name="chaos-victim")
            victim.start()
            victim["src"].push(p_kill)
            deadline = time.monotonic() + 30
            while (len(victim["out"].frames) < 2
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            kill_chunks = len(victim["out"].frames)
            assert kill_chunks >= 2
            victim_frames = list(victim["out"].frames)
            victim.stop()  # mid-decode kill

            # deadline victim (started AFTER the kill so a freed slot is
            # coming): budget far below the full generation's decode time
            evict = parse_pipeline(
                f"appsrc name=src ! tensor_query_client name=q "
                f"port={port} connect-type=tcp stream=true timeout=0.5 "
                "retries=0 ! tensor_sink name=out", name="chaos-evict")
            evict.start()
            evict["src"].push(p_evict)
            evict["src"].end_of_stream()
            try:
                evict.wait(timeout=30)
            except Exception:
                pass  # a lost eviction race surfaces as client timeout
            evict_frames = list(evict["out"].frames)
            evict_health = evict.health()["q"]
            evict.stop()

            t_fin.join(timeout=120)

            # engine settles: kill-cancel feedback frees the slot
            deadline = time.monotonic() + 20
            gen_health = server.health()["gen"]
            while time.monotonic() < deadline:
                gen_health = server.health()["gen"]
                if (gen_health["gen_occupied"] == 0
                        and gen_health["gen_waiting"] == 0):
                    break
                time.sleep(0.02)
        finally:
            server.stop()

        # finisher: exact full completion
        toks = _stream_tokens(results["fin"])
        np.testing.assert_array_equal(toks, sim_oracle(sim, p_fin, n))

        # killed stream: the chunks that DID arrive are an exact oracle
        # prefix (no contamination before the kill)
        got = np.concatenate(
            [np.asarray(f.tensors[0]) for f in victim_frames
             if f.tensors], axis=1)
        np.testing.assert_array_equal(
            got, sim_oracle(sim, p_kill, got.shape[1]))

        # evicted stream: typed expiry, partial tokens exact.  How many
        # tokens land before the budget blows depends on when the killed
        # victim's slot frees (cancel-feedback detection is ~0.1s but
        # races the 0.5s budget on a slow box) — zero tokens is a LEGAL
        # outcome of that race (the engine logs "evicted after 0
        # token(s)" and the final marker is tensor-less; the chaos
        # harness's check_exact tolerates it the same way).  What is
        # deterministic: the typed-expiry answer, exact tokens_done
        # accounting, and oracle-prefix integrity of whatever DID land.
        assert evict_frames, "eviction must ANSWER the stream"
        last = evict_frames[-1].meta
        assert last["final"] is True
        assert last["evicted"] == "deadline"
        assert last["deadline_expired"] is True
        etok_arrays = [
            np.asarray(f.tensors[0]) for f in evict_frames if f.tensors
        ]
        n_etoks = sum(a.shape[1] for a in etok_arrays)
        assert n_etoks < n  # the budget really cut the stream short
        if etok_arrays:
            etoks = np.concatenate(etok_arrays, axis=1)
            np.testing.assert_array_equal(
                etoks, sim_oracle(sim, p_evict, etoks.shape[1]))
        assert n_etoks == last["tokens_done"]
        assert evict_health["deadline_expired"] >= 1

        # server-side verdict: every slot freed, counters exact.  The
        # evict stream JOINS only when a slot freed inside its budget:
        # delivered tokens imply a join; a waiting-queue eviction
        # legally leaves joins at 2 (same race as above).
        assert gen_health["gen_occupied"] == 0
        assert gen_health["gen_joins"] in ((3,) if n_etoks else (2, 3))
        assert gen_health["gen_completed"] == 1
        assert gen_health["gen_evicted"] == 1
        assert gen_health["gen_cancelled"] == 1
        assert gen_health["gen_decode_compiles"] <= 4
