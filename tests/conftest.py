"""Test harness config: force an 8-device CPU mesh so sharding/collective
paths are exercised without TPU hardware (driver benches separately on TPU).

Must run before jax is first imported anywhere in the test process.
"""

import os
import sys

# HARD override: the container pins jax to the real TPU tunnel ("axon") and
# its sitecustomize force-updates jax.config jax_platforms="axon,cpu" at
# interpreter start — the env var alone is overridden.  Tests must never
# claim the chip, so set BOTH the env var and (after import) the config.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import threading  # noqa: E402
import time  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ---------------------------------------------------------------------------
# Perf-floor load shielding (by construction, not operator discipline):
# `pytest -m perf` timing tests measure real clocks, so a fleet/chaos suite
# interleaved INTO the perf block by pytest-randomly turns ambient load
# into flaky floor failures.  Two guards:
#
# 1. a hookwrapper collection hook (runs AFTER every other implementation,
#    pytest-randomly's shuffle included) gathers perf-marked items into
#    ONE CONTIGUOUS block at the position of the first perf item, so no
#    fleet/chaos test can run BETWEEN two timing floors (moving the block
#    to the very front was tried and is itself a flake source: timing
#    floors in a cold process measure thread-pool/allocator warmup);
# 2. an autouse fixture makes each perf test wait (bounded) until no
#    framework threads from a previous test are still winding down.
#
# When BISECTING a perf failure, additionally run the perf-marked files
# with `-p no:randomly` (tier-1 already does): pytest-randomly reseeds
# NumPy/random per test, and while guard 1 keeps the perf BLOCK
# contiguous, a shuffled neighborhood still changes which suites warmed
# the process before the block — the contiguity of the block is pinned
# by tests/test_perf_truth.py::test_perf_block_stays_contiguous.
# ---------------------------------------------------------------------------
@pytest.hookimpl(hookwrapper=True)
def pytest_collection_modifyitems(config, items):
    yield  # let every other plugin (randomization included) reorder first
    perf = [it for it in items if it.get_closest_marker("perf")]
    if not perf or len(perf) == len(items):
        return  # nothing to shield (or a pure `-m perf` run)
    first = next(
        i for i, it in enumerate(items) if it.get_closest_marker("perf"))
    rest = [it for it in items if not it.get_closest_marker("perf")]
    pos = min(first, len(rest))
    items[:] = rest[:pos] + perf + rest[pos:]


@pytest.fixture(autouse=True)
def _perf_load_shield(request):
    """Perf-marked tests start on a quiet box: bounded wait for framework
    threads (fleet servers, pumps, stagers) from earlier tests to exit."""
    if request.node.get_closest_marker("perf") is None:
        yield
        return
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline and _live_framework_threads():
        time.sleep(0.05)
    yield


# ---------------------------------------------------------------------------
# Leak guard (zero-downtime operations contract): drain/swap/rolling-restart
# must not strand worker threads or sockets.  The lifecycle/e2e test modules
# autouse this module-scoped fixture, so the check runs inside tier-1
# alongside the lint gates.
# ---------------------------------------------------------------------------
#: thread-name prefixes outside our control (library pools, pytest
#: internals).  Framework threads are all explicitly named (segment
#: workers by element, "-watchdog", "tcpq-*", "-model-stage", pumps), so
#: anonymous "Thread-N" / executor workers are not our leak signal.
_LEAK_IGNORE = (
    "MainThread", "Thread-", "ThreadPool", "Dummy", "asyncio",
    "pydevd", "raylet",
)


def _live_framework_threads() -> set:
    return {
        t.name for t in threading.enumerate()
        if t.is_alive() and not t.name.startswith(_LEAK_IGNORE)
    }


def _socket_fd_count() -> int:
    """Open socket fds of this process (-1 = unsupported platform)."""
    fd_dir = "/proc/self/fd"
    try:
        fds = os.listdir(fd_dir)
    except OSError:
        return -1
    n = 0
    for fd in fds:
        try:
            if os.readlink(os.path.join(fd_dir, fd)).startswith("socket:"):
                n += 1
        except OSError:
            continue
    return n


def _live_metrics_servers() -> int:
    """Open telemetry exposition servers (each owns a listener socket +
    a '<name>-metrics' thread).  Lazy import: modules that never touch
    telemetry must not pay for it."""
    mod = sys.modules.get("nnstreamer_tpu.core.telemetry")
    if mod is None:
        return 0
    return mod.live_server_count()


@pytest.fixture(scope="module")
def module_leak_check():
    """Assert the module left no framework threads, no net-new socket
    fds, and no open metrics-exposition server behind (bounded
    convergence wait — teardown is asynchronous).

    The metrics endpoint is covered twice: its serve thread is named
    ``<owner>-metrics`` (visible to the thread census — never a
    ``Thread-N`` the ignore list skips) and its listener socket counts
    in the fd census; the explicit server count makes the failure
    message say WHAT leaked instead of just 'a socket'."""
    threads_before = _live_framework_threads()
    sockets_before = _socket_fd_count()
    metrics_before = _live_metrics_servers()
    yield
    deadline = time.monotonic() + 8.0
    leaked_threads: set = set()
    sockets_now = sockets_before
    metrics_now = metrics_before
    while time.monotonic() < deadline:
        leaked_threads = _live_framework_threads() - threads_before
        sockets_now = _socket_fd_count()
        metrics_now = _live_metrics_servers()
        if not leaked_threads and metrics_now <= metrics_before and (
                sockets_before < 0 or sockets_now <= sockets_before):
            break
        time.sleep(0.05)
    assert metrics_now <= metrics_before, (
        f"leaked metrics exposition server(s) after module: "
        f"{metrics_before} -> {metrics_now} (Pipeline.stop() must close "
        "the endpoint)"
    )
    assert not leaked_threads, (
        f"leaked framework threads after module: {sorted(leaked_threads)}"
    )
    if sockets_before >= 0:
        assert sockets_now <= sockets_before, (
            f"leaked sockets after module: {sockets_before} -> {sockets_now}"
        )
