"""Test harness config: force an 8-device CPU mesh so sharding/collective
paths are exercised without TPU hardware (driver benches separately on TPU).

Must run before jax is first imported anywhere in the test process.
"""

import os
import sys

# HARD override: the container pins jax to the real TPU tunnel ("axon") and
# its sitecustomize force-updates jax.config jax_platforms="axon,cpu" at
# interpreter start — the env var alone is overridden.  Tests must never
# claim the chip, so set BOTH the env var and (after import) the config.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
