"""Test harness config: force an 8-device CPU mesh so sharding/collective
paths are exercised without TPU hardware (driver benches separately on TPU).

Must run before jax is first imported anywhere in the test process.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
