"""Worker for test_multihost_training.py: one simulated host of a
dp-across-hosts × tp/sp-within-host transformer training job.

The SAME sharded train step used single-process
(``models/transformer.make_train_step``) runs unchanged over a hybrid
DCN×ICI mesh — gradient psum crosses processes via the distributed
runtime's collectives; params/opt state stay sharded."""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from nnstreamer_tpu.parallel import multihost  # noqa: E402


def main() -> None:
    multihost.initialize(platform="cpu")

    import jax

    from nnstreamer_tpu.models.transformer import (
        TransformerConfig,
        make_train_step,
    )

    nproc = multihost.process_count()
    mesh = multihost.hybrid_mesh({"tp": 2, "sp": -1}, {"dp": nproc})

    import jax.numpy as jnp

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
        max_seq=16, dtype=jnp.float32,
    )
    step, params, opt_state, data_sh = make_train_step(mesh, cfg)

    # every process materializes the same global batch; device_put onto
    # the global sharding places only this host's addressable shards
    batch = 4 * nproc
    rng = np.random.default_rng(0)  # SAME seed everywhere — global data
    losses = []
    for i in range(3):
        tokens = jax.device_put(
            rng.integers(0, cfg.vocab, (batch, cfg.max_seq)).astype(
                np.int32
            ),
            data_sh,
        )
        params, opt_state, loss = step(params, opt_state, tokens)
        losses.append(float(loss))

    multihost.barrier("trained")
    print(
        "RESULT "
        + json.dumps({
            "pid": multihost.process_index(),
            "losses": losses,
            "mesh": {k: int(v) for k, v in mesh.shape.items()},
        }),
        flush=True,
    )


if __name__ == "__main__":
    main()
