"""Round-5 property-parity additions: reference props now honored.

Each test exercises the BEHAVIOR, not just the declaration — the parity
contract is that a reference pipeline text using these props works here
with the same semantics (reference cites in each element's docstring).
"""

import os
import time

import numpy as np
import pytest

import _env_capabilities

from nnstreamer_tpu.backends.custom_easy import (
    register_custom_easy,
    unregister_custom_easy,
)
from nnstreamer_tpu.core.buffer import TensorFrame
from nnstreamer_tpu.pipeline import parse_pipeline
from nnstreamer_tpu.pipeline.element import ElementError, make_element


def _run(pipeline_text, frames, name="pp"):
    pipe = parse_pipeline(pipeline_text, name=name)
    pipe.start()
    got = []
    pipe["out"].connect_new_data(lambda f: got.append(f))
    for fr in frames:
        pipe["src"].push(fr)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()
    return got


@pytest.mark.skipif(
    not _env_capabilities.has_reference_tree(),
    reason="prop-parity audit needs the reference checkout at "
    + _env_capabilities.REFERENCE_TREE,
)
def test_no_unannotated_reference_prop_gaps():
    """tools/prop_parity.py --check: every reference element property is
    either present, renamed, or has a curated covered-by annotation."""
    import subprocess
    import sys as _sys

    r = subprocess.run(
        [_sys.executable, "tools/prop_parity.py", "--check"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert r.returncode == 0, r.stdout + r.stderr


class TestCommonSilent:
    def test_every_element_answers_silent(self):
        from nnstreamer_tpu.pipeline.element import ELEMENT_TYPES

        el = make_element("tensor_converter")
        assert el.get_property("silent") is True
        el.set_property("silent", "false")
        assert el.get_property("silent") is False
        # spot-check breadth: a sample across layers
        for factory in ("tensor_demux", "tensor_sink", "appsrc", "queue"):
            assert factory in ELEMENT_TYPES
            make_element(factory).set_property("silent", "false")

    def test_silent_false_lowers_logger_level(self):
        import logging

        el = make_element("tensor_sink", name="silent-probe")
        el.set_property("silent", False)
        assert el.log.level == logging.DEBUG
        el.set_property("silent", True)
        assert el.log.level == logging.NOTSET


class TestTransformApply:
    def test_apply_subset_passthrough_rest(self):
        el = make_element(
            "tensor_transform", mode="arithmetic", option="mul:2", apply="0",
        )
        el.start()
        frame = TensorFrame([
            np.ones((4,), np.float32), np.ones((4,), np.float32),
        ])
        out = el.transform(frame)
        assert np.allclose(np.asarray(out.tensors[0]), 2.0)
        assert np.allclose(np.asarray(out.tensors[1]), 1.0)  # untouched


class TestRateCounters:
    def test_counters_readable_and_read_only(self):
        el = make_element("tensor_rate", framerate="10/1", throttle="false")
        el.start()
        for i in range(5):
            f = TensorFrame([np.zeros((2,), np.float32)])
            f.pts = i * 0.05  # 20 fps in -> 10 fps out drops
            el.transform(f)
        assert el.get_property("in") == 5
        assert el.get_property("out") + el.get_property("drop") >= 4
        with pytest.raises(ElementError):
            el.set_property("in", 7)


class TestSinkSignals:
    def test_emit_signal_false_stores_but_never_calls(self):
        sink = make_element("tensor_sink")
        sink.set_property("emit-signal", "false")
        calls = []
        sink.connect_new_data(lambda f: calls.append(f))
        sink.render(TensorFrame([np.zeros((1,), np.float32)]))
        assert len(sink.frames) == 1 and calls == []

    def test_signal_rate_throttles_callbacks(self):
        sink = make_element("tensor_sink")
        sink.set_property("signal-rate", 5)  # >= 200ms between signals
        calls = []
        sink.connect_new_data(lambda f: calls.append(f))
        for _ in range(10):
            sink.render(TensorFrame([np.zeros((1,), np.float32)]))
        assert len(sink.frames) == 10
        assert len(calls) <= 2  # burst collapses to ~1 signal


class TestSplitTensorpick:
    def test_pick_reorders_and_drops_segments(self):
        register_custom_easy("pp_id", lambda xs: [np.asarray(xs[0])])
        try:
            pipe = parse_pipeline(
                "appsrc name=src ! tensor_split name=sp tensorseg=2,1,3 "
                "tensorpick=2,0 option=0 ! tensor_sink name=out",
                name="pick",
            )
            sp = pipe["sp"]
            # second pad: the pick list maps pads -> segments
            sink2 = make_element("tensor_sink", name="out2")
            pipe.add(sink2)
            sp.link(sink2, src_pad=1)
            pipe.start()
            pipe["src"].push(np.arange(6, dtype=np.float32))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=30)
            a = np.asarray(pipe["out"].frames[0].tensors[0])
            b = np.asarray(sink2.frames[0].tensors[0])
            pipe.stop()
            assert a.tolist() == [3.0, 4.0, 5.0]  # segment 2 first
            assert b.tolist() == [0.0, 1.0]       # then segment 0
        finally:
            unregister_custom_easy("pp_id")

    def test_pick_out_of_range_fails_loud(self):
        el = make_element("tensor_split", tensorseg="2,2", tensorpick="3")
        with pytest.raises(ElementError):
            el.handle_frame(0, TensorFrame([np.zeros((4,), np.float32)]))


class TestConverterSetTimestamp:
    def test_stamps_when_missing_and_preserves_existing(self):
        el = make_element("tensor_converter")
        el.start()
        (_, out), = el.handle_frame(0, TensorFrame([np.zeros(3, np.uint8)]))
        assert out.pts is not None and out.pts >= 0.0
        f2 = TensorFrame([np.zeros(3, np.uint8)])
        f2.pts = 42.0
        (_, out2), = el.handle_frame(0, f2)
        assert out2.pts == 42.0

    def test_opt_out(self):
        el = make_element("tensor_converter")
        el.set_property("set-timestamp", "false")
        el.start()
        (_, out), = el.handle_frame(0, TensorFrame([np.zeros(3, np.uint8)]))
        assert out.pts is None

    def test_restart_resets_pts_origin(self):
        el = make_element("tensor_converter")
        el.start()
        el.handle_frame(0, TensorFrame([np.zeros(3, np.uint8)]))
        time.sleep(0.05)
        el.start()  # restarted pipeline: pts restarts near 0
        (_, out), = el.handle_frame(0, TensorFrame([np.zeros(3, np.uint8)]))
        assert out.pts < 0.05


class TestFilterManualInfo:
    def test_declares_io_for_inference_free_backend(self):
        def double(inputs):
            return [np.asarray(inputs[0], np.float32) * 2]

        register_custom_easy("pp_double", double)
        try:
            got = _run(
                "appsrc name=src ! "
                "tensor_filter framework=custom-easy model=pp_double "
                "input=4 input-type=float32 inputname=x "
                "output=4 output-type=float32 ! "
                "tensor_sink name=out",
                [np.ones((4,), np.float32)],
            )
            assert np.allclose(np.asarray(got[0].tensors[0]), 2.0)
        finally:
            unregister_custom_easy("pp_double")

    @pytest.mark.parametrize("out_dims,out_type", [
        ("5", "float32"),   # shape mismatch
        ("4", "int8"),      # dtype mismatch (must not be silently ignored)
    ])
    def test_output_mismatch_fails_loud(self, out_dims, out_type):
        from nnstreamer_tpu.backends.jax_xla import (
            register_jax_model,
            unregister_jax_model,
        )

        register_jax_model(
            "pp_m", lambda p, xs: [xs[0] * 2.0], {},
            [((4,), "float32")], [((4,), "float32")],
        )
        try:
            el = make_element(
                "tensor_filter", framework="jax-xla", model="pp_m",
                output=out_dims, output_type=out_type,
            )
            with pytest.raises(ElementError, match="does not match"):
                el.start()
        finally:
            unregister_jax_model("pp_m")

    def test_rank_and_layout_validation(self):
        el = make_element("tensor_filter", framework="jax-xla")
        el.set_property("inputlayout", "NCHW")
        el._check_layouts()
        el.set_property("inputlayout", "WEIRD")
        with pytest.raises(ElementError, match="unknown layout"):
            el._check_layouts()
        assert el._apply_rank((1, 1, 4), 2) == (1, 4)
        assert el._apply_rank((4,), 3) == (1, 1, 4)
        with pytest.raises(ElementError):
            el._apply_rank((2, 4), 1)


class TestConfigFile(object):
    def test_filter_config_file_explicit_wins(self, tmp_path):
        def ident(inputs):
            return [np.asarray(inputs[0])]

        register_custom_easy("pp_cfg", ident)
        try:
            cfg = tmp_path / "f.conf"
            cfg.write_text(
                "# comment\nmax-batch=8\nframework=custom-easy\n"
                "model=pp_cfg\ninput=4\ninput-type=float32\n"
                "output=4\noutput-type=float32\n"
            )
            el = make_element(
                "tensor_filter", **{"config-file": str(cfg), "max-batch": 2}
            )
            el.start()
            try:
                assert el.props["max-batch"] == 2   # explicit wins
                assert el.props["model"] == "pp_cfg"  # file applied
            finally:
                el.stop()
        finally:
            unregister_custom_easy("pp_cfg")

    def test_decoder_config_file(self, tmp_path):
        cfg = tmp_path / "d.conf"
        cfg.write_text("mode=octet_stream\n")
        el = make_element("tensor_decoder", **{"config-file": str(cfg)})
        el.start()
        assert el.props["mode"] == "octet_stream"

    def test_bad_line_fails_with_location(self, tmp_path):
        cfg = tmp_path / "bad.conf"
        cfg.write_text("mode=octet_stream\nnot a kv line\n")
        el = make_element("tensor_decoder", **{"config-file": str(cfg)})
        with pytest.raises(ElementError, match="bad.conf:2"):
            el.start()

    def test_inline_hash_preserved_in_values(self, tmp_path):
        # '#' only comments FULL lines; values may contain it
        cfg = tmp_path / "hash.conf"
        cfg.write_text("# a comment\ncustom=color:#ff0000\n")
        el = make_element("tensor_filter", **{"config-file": str(cfg)})
        el._apply_config_file()
        assert el.props["custom"] == "color:#ff0000"


class TestServerSinkLimit:
    def test_limit_drops_excess_answers(self):
        from nnstreamer_tpu.distributed.service import QueryServerCore

        core = QueryServerCore(0)
        with core._pending_client(
            [TensorFrame([np.zeros((1,), np.float32)])]
        ) as q:
            cid = next(iter(core._pending))
            f = TensorFrame([np.zeros((1,), np.float32)])
            assert core.resolve(cid, f, limit=2)
            assert core.resolve(cid, f, limit=2)
            assert not core.resolve(cid, f, limit=2)  # at limit: dropped
            assert q.qsize() == 2


class TestTrainerReadyToComplete:
    def test_early_finish(self, tmp_path):
        import json

        cfg = tmp_path / "cfg.json"
        cfg.write_text(json.dumps({
            "arch": "mnist_cnn",
            "arch_props": {"dtype": "float32", "classes": "2"},
            "batch_size": 4,
        }))
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_trainer name=t framework=jax "
            f"model-config={cfg} num-inputs=1 num-labels=1 "
            "num-training-samples=4 epochs=100 ! tensor_sink name=out",
            name="rtc",
        )
        pipe.start()
        t = pipe["t"]
        rng = np.random.default_rng(0)
        for i in range(4):
            f = TensorFrame([
                rng.normal(0, 1, (28, 28, 1)).astype(np.float32),
                np.eye(2, dtype=np.float32)[i % 2],
            ])
            pipe["src"].push(f)
        deadline = time.time() + 30
        while not t._created and time.time() < deadline:
            time.sleep(0.05)
        assert t._created
        # finish NOW, long before 100 epochs
        t.set_property("ready-to-complete", "true")
        assert t.training_complete.wait(timeout=60)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        pipe.stop()


class TestMqttAliases:
    def test_reference_spellings_accepted_and_win(self):
        sink = make_element(
            "mqttsink", **{"pub-topic": "t", "mqtt-qos": 1, "qos": 0}
        )
        assert sink._effective_qos() == 1
        src = make_element("mqttsrc", **{"sub-topic": "t"})
        for k, v in [
            ("cleansession", "false"), ("keep-alive-interval", 30),
            ("mqtt-qos", 1), ("debug", "true"), ("is-live", "true"),
        ]:
            src.set_property(k, v)
        assert src.props["cleansession"] is False

    def test_ntp_sync_false_skips_receiver_rebase(self):
        # a 0.0 base epoch in the header means "no shared epoch": the
        # receiver must NOT shift pts by -receiver_epoch (≈ -1.7e9 s)
        import queue as _q
        import struct

        from nnstreamer_tpu.distributed import wire
        from nnstreamer_tpu.elements.mqtt import _HDR, _MAGIC

        src = make_element("mqttsrc", **{"sub-topic": "t", "num-buffers": 1,
                                         "sub-timeout": 200})
        src._base_epoch = time.time()
        f = TensorFrame([np.zeros((1,), np.float32)])
        f.pts = 1.25
        payload = _HDR.pack(_MAGIC, 0.0, time.time()) + wire.encode_frame(f)
        src._q = _q.Queue(4)
        src._q.put(payload)
        got = next(iter(src.frames()))
        assert got.pts == 1.25  # untouched

    def test_max_buffer_size_guard(self):
        sink = make_element(
            "mqttsink", **{"pub-topic": "t", "max-buffer-size": 8}
        )

        sent = []

        class FakeClient:
            def publish(self, topic, payload, retain=False, qos=0):
                sent.append(payload)

        sink._client = FakeClient()
        sink._encode = lambda f: b"x" * 100  # encoded >> cap
        sink.render(TensorFrame([np.zeros((1,), np.uint8)]))
        assert sent == []  # dropped with warning, not published


class TestIioTriggerNumber:
    def test_trigger_number_resolves_sysfs_name(self, tmp_path):
        # current_trigger wants the trigger's NAME file contents, not the
        # directory name
        tdir = tmp_path / "trigger3"
        tdir.mkdir()
        (tdir / "name").write_text("sysfstrig3\n")
        el = make_element(
            "tensor_src_iio",
            **{"trigger-number": 3, "iio-base-dir": str(tmp_path)},
        )
        assert el._resolve_trigger() == "sysfstrig3"

    def test_trigger_number_falls_back_to_dir_name(self, tmp_path):
        el = make_element(
            "tensor_src_iio",
            **{"trigger-number": 7, "iio-base-dir": str(tmp_path)},
        )
        assert el._resolve_trigger() == "trigger7"

    def test_explicit_trigger_name_wins(self, tmp_path):
        el = make_element(
            "tensor_src_iio",
            **{"trigger": "mytrig", "trigger-number": 3,
               "iio-base-dir": str(tmp_path)},
        )
        assert el._resolve_trigger() == "mytrig"
