"""Multi-host (multi-process) runtime: 2 simulated hosts x 4 virtual CPU
devices on localhost, gloo collectives across processes.

Reference analog: the reference's distributed tests run multi-"node" as
multiple processes on localhost (SURVEY §4 "no real cluster"); same shape
here, but the payload is the real JAX multi-process runtime — a hybrid
DCN x ICI mesh with dp crossing processes — not a socket transport mock.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

import _env_capabilities

pytestmark = pytest.mark.skipif(
    not _env_capabilities.multihost_cpu_ok(),
    reason="multi-process CPU gang needs >= 2 cores (workers get "
    "virtual devices via jax_num_cpu_devices or the XLA_FLAGS "
    "fallback; on one core the gang starves gloo barriers)",
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(ROOT, "tests", "_multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_hybrid_mesh():
    nproc, nlocal = 2, 4
    coord = f"127.0.0.1:{_free_port()}"
    procs = []
    for pid in range(nproc):
        env = dict(
            os.environ,
            NNS_TPU_COORDINATOR=coord,
            NNS_TPU_NUM_PROCS=str(nproc),
            NNS_TPU_PROC_ID=str(pid),
            NNS_TPU_LOCAL_DEVICES=str(nlocal),
            JAX_PLATFORMS="cpu",
        )
        # the parent's 8-device XLA_FLAGS would fight jax_num_cpu_devices
        env.pop("XLA_FLAGS", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    results = {}
    try:
        for pid, p in enumerate(procs):
            out, err = p.communicate(timeout=240)
            assert p.returncode == 0, f"worker {pid} failed:\n{err[-2000:]}"
            line = [ln for ln in out.splitlines() if ln.startswith("RESULT ")]
            assert line, f"worker {pid} printed no RESULT:\n{out[-500:]}"
            results[pid] = json.loads(line[-1][len("RESULT "):])
    finally:
        # a failed/hung worker must not orphan its peers (they block in
        # gloo collectives against the dead coordinator, holding the port)
        for q in procs:
            if q.poll() is None:
                q.kill()

    assert results[0]["primary"] and not results[1]["primary"]
    for pid, r in results.items():
        assert r["nproc"] == nproc
        assert r["global_devices"] == nproc * nlocal
        # dp-mean across hosts must agree everywhere (same global program)
        assert abs(r["loss"] - results[0]["loss"]) < 1e-6
