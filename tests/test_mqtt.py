"""MQTT transport (mini client/broker) + mqttsink/mqttsrc elements."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient, topic_matches
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def broker():
    b = MiniBroker()
    yield b
    b.close()


class TestTopicMatch:
    def test_wildcards(self):
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/c")
        assert topic_matches("a/+", "a/b")
        assert not topic_matches("a/+", "a/b/c")
        assert topic_matches("a/#", "a/b/c")
        assert topic_matches("#", "anything/at/all")
        assert not topic_matches("a/b/c", "a/b")


class TestClientBroker:
    def test_pub_sub_roundtrip(self, broker):
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("nns/#", lambda t, p: (got.append((t, p)), ev.set()))
        time.sleep(0.05)
        pub = MqttClient(broker.host, broker.port)
        pub.publish("nns/test", b"hello")
        assert ev.wait(5)
        assert got == [("nns/test", b"hello")]
        pub.close()
        sub.close()

    def test_retained_message(self, broker):
        pub = MqttClient(broker.host, broker.port)
        pub.publish("cfg/x", b"state", retain=True)
        time.sleep(0.05)
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("cfg/+", lambda t, p: (got.append(p), ev.set()))
        assert ev.wait(5)
        assert got == [b"state"]
        pub.close()
        sub.close()

    def test_ping(self, broker):
        c = MqttClient(broker.host, broker.port)
        c.ping()  # must not raise / kill the connection
        time.sleep(0.05)
        c.publish("t", b"x")
        c.close()


class TestMqttElements:
    def test_pipeline_pubsub(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=nns/stream num-buffers=3 sub-timeout=15000 ! "
            "tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.2)  # let the subscription land

        tx = parse_pipeline(
            f"appsrc name=src ! mqttsink host={broker.host} "
            f"port={broker.port} pub-topic=nns/stream"
        )
        tx.start()
        for i in range(3):
            tx["src"].push(np.full((4,), i, np.float32), pts=i * 0.1)
        tx["src"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()

        rx.wait(timeout=30)
        rx.stop()
        frames = rx["out"].frames
        assert len(frames) == 3
        np.testing.assert_allclose(frames[1].tensors[0], np.full((4,), 1.0))
        # timestamp rebasing: sender clock mapped into receiver domain —
        # relative spacing preserved
        assert frames[1].pts - frames[0].pts == pytest.approx(0.1, abs=0.02)
        assert "mqtt-latency-s" in frames[0].meta

    def test_src_timeout_eos(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=never/published sub-timeout=300 ! tensor_sink name=out"
        )
        rx.start()
        rx.wait(timeout=15)  # EOS via sub-timeout
        rx.stop()
        assert rx["out"].frames == []
