"""MQTT transport (mini client/broker) + mqttsink/mqttsrc elements."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient, topic_matches
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def broker():
    b = MiniBroker()
    yield b
    b.close()


class TestTopicMatch:
    def test_wildcards(self):
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/c")
        assert topic_matches("a/+", "a/b")
        assert not topic_matches("a/+", "a/b/c")
        assert topic_matches("a/#", "a/b/c")
        assert topic_matches("#", "anything/at/all")
        assert not topic_matches("a/b/c", "a/b")


class TestClientBroker:
    def test_pub_sub_roundtrip(self, broker):
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("nns/#", lambda t, p: (got.append((t, p)), ev.set()))
        time.sleep(0.05)
        pub = MqttClient(broker.host, broker.port)
        pub.publish("nns/test", b"hello")
        assert ev.wait(5)
        assert got == [("nns/test", b"hello")]
        pub.close()
        sub.close()

    def test_retained_message(self, broker):
        pub = MqttClient(broker.host, broker.port)
        pub.publish("cfg/x", b"state", retain=True)
        time.sleep(0.05)
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("cfg/+", lambda t, p: (got.append(p), ev.set()))
        assert ev.wait(5)
        assert got == [b"state"]
        pub.close()
        sub.close()

    def test_ping(self, broker):
        c = MqttClient(broker.host, broker.port)
        c.ping()  # must not raise / kill the connection
        time.sleep(0.05)
        c.publish("t", b"x")
        c.close()


class TestMqttElements:
    def test_pipeline_pubsub(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=nns/stream num-buffers=3 sub-timeout=15000 ! "
            "tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.2)  # let the subscription land

        tx = parse_pipeline(
            f"appsrc name=src ! mqttsink host={broker.host} "
            f"port={broker.port} pub-topic=nns/stream"
        )
        tx.start()
        for i in range(3):
            tx["src"].push(np.full((4,), i, np.float32), pts=i * 0.1)
        tx["src"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()

        rx.wait(timeout=30)
        rx.stop()
        frames = rx["out"].frames
        assert len(frames) == 3
        np.testing.assert_allclose(frames[1].tensors[0], np.full((4,), 1.0))
        # timestamp rebasing: sender clock mapped into receiver domain —
        # relative spacing preserved
        assert frames[1].pts - frames[0].pts == pytest.approx(0.1, abs=0.02)
        assert "mqtt-latency-s" in frames[0].meta

    def test_src_timeout_eos(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=never/published sub-timeout=300 ! tensor_sink name=out"
        )
        rx.start()
        rx.wait(timeout=15)  # EOS via sub-timeout
        rx.stop()
        assert rx["out"].frames == []


def _restart_broker(port, timeout=8.0):
    """Rebind the broker port, retrying while old sockets drain."""
    deadline = time.time() + timeout
    while True:
        try:
            return MiniBroker(port=port)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


class TestQos1:
    def test_puback_drains_pending(self, broker):
        sub_got = []
        rx = MqttClient(broker.host, broker.port)
        rx.subscribe("q/1", lambda t, p: sub_got.append(p))
        tx = MqttClient(broker.host, broker.port)
        time.sleep(0.1)
        tx.publish("q/1", b"hello", qos=1)
        deadline = time.time() + 5
        while (tx.unacked() or len(sub_got) < 1) and time.time() < deadline:
            time.sleep(0.02)
        assert tx.unacked() == 0  # PUBACK received
        assert sub_got == [b"hello"]
        tx.close(); rx.close()

    def test_qos0_unaffected(self, broker):
        tx = MqttClient(broker.host, broker.port)
        tx.publish("q/0", b"x", qos=0)
        assert tx.unacked() == 0
        tx.close()


class TestSubscriberQos1:
    """Subscriber-side QoS 1 (MQTT 3.1.1 §3.8.4/§4.3.2): granted in
    SUBACK, deliveries carry packet ids and retransmit until PUBACK, and
    persistent sessions survive subscriber death with no message loss."""

    @staticmethod
    def _raw_connect(broker, cid, clean=True):
        import socket as _socket
        import struct as _struct

        from nnstreamer_tpu.distributed import mqtt as m

        s = _socket.create_connection((broker.host, broker.port), timeout=5)
        var = (
            m._mqtt_str("MQTT") + bytes([4])
            + bytes([0x02 if clean else 0x00])
            + _struct.pack(">H", 60) + m._mqtt_str(cid)
        )
        s.sendall(bytes([m.CONNECT << 4]) + m._encode_len(len(var)) + var)
        ptype, _, body = m._read_packet(s)
        assert ptype == m.CONNACK
        return s, body

    @staticmethod
    def _raw_subscribe(s, pattern, qos):
        import struct as _struct

        from nnstreamer_tpu.distributed import mqtt as m

        var = _struct.pack(">H", 7) + m._mqtt_str(pattern) + bytes([qos])
        s.sendall(
            bytes([(m.SUBSCRIBE << 4) | 0x2]) + m._encode_len(len(var)) + var
        )
        ptype, _, body = m._read_packet(s)
        assert ptype == m.SUBACK
        return body[2:]  # granted QoS list

    def test_suback_grants_requested_qos(self, broker):
        s, _ = self._raw_connect(broker, "raw-grant")
        try:
            assert self._raw_subscribe(s, "g/1", 1) == bytes([1])
            assert self._raw_subscribe(s, "g/0", 0) == bytes([0])
            assert self._raw_subscribe(s, "g/2", 2) == bytes([1])  # capped
        finally:
            s.close()

    def test_delivery_has_packet_id_and_dup_retransmit(self):
        from nnstreamer_tpu.distributed import mqtt as m

        broker = MiniBroker(retransmit_s=0.3)
        try:
            s, _ = self._raw_connect(broker, "raw-sub")
            self._raw_subscribe(s, "d/t", 1)
            tx = MqttClient(broker.host, broker.port)
            tx.publish("d/t", b"payload", qos=1)
            # first delivery: QoS 1, packet id, no DUP
            ptype, flags, body = m._read_packet(s)
            assert ptype == m.PUBLISH and (flags >> 1) & 0x3 == 1
            topic, payload, pid = m._parse_publish(flags, body)
            assert (topic, payload) == ("d/t", b"payload")
            assert pid is not None and not (flags & 0x8)
            # no PUBACK sent -> broker must retransmit with DUP, same pid
            ptype, flags, body = m._read_packet(s)
            assert ptype == m.PUBLISH and flags & 0x8
            _, _, pid2 = m._parse_publish(flags, body)
            assert pid2 == pid
            # ack it; the redelivery loop must go quiet
            import struct as _struct

            s.sendall(bytes([m.PUBACK << 4, 2]) + _struct.pack(">H", pid))
            s.settimeout(1.0)
            with pytest.raises(OSError):
                m._read_packet(s)  # nothing further arrives
            tx.close()
            s.close()
        finally:
            broker.close()

    def test_qos0_subscription_downgrades_delivery(self, broker):
        from nnstreamer_tpu.distributed import mqtt as m

        s, _ = self._raw_connect(broker, "raw-q0")
        try:
            self._raw_subscribe(s, "q0/t", 0)
            tx = MqttClient(broker.host, broker.port)
            tx.publish("q0/t", b"x", qos=1)  # min(1, 0) = QoS 0 out
            ptype, flags, body = m._read_packet(s)
            assert ptype == m.PUBLISH and (flags >> 1) & 0x3 == 0
            _, _, pid = m._parse_publish(flags, body)
            assert pid is None
            tx.close()
        finally:
            s.close()

    def test_slow_acker_overflow_queues_then_promotes(self, monkeypatch):
        """A connected subscriber that stops PUBACKing must not grow the
        inflight map unboundedly: overflow parks in the session queue and
        is promoted (delivered) once acks free inflight slots."""
        import struct as _struct

        from nnstreamer_tpu.distributed import mqtt as m
        from nnstreamer_tpu.distributed.mqtt import _BrokerSession

        monkeypatch.setattr(_BrokerSession, "INFLIGHT_LIMIT", 3)
        broker = MiniBroker(retransmit_s=0.2)
        try:
            s, _ = self._raw_connect(broker, "slow-acker")
            self._raw_subscribe(s, "o/t", 1)
            tx = MqttClient(broker.host, broker.port)
            n = 10
            for i in range(n):
                tx.publish("o/t", f"p{i}".encode(), qos=1)
            assert tx.drain(5) == 0
            with broker._lock:
                sess = broker._sessions["slow-acker"]
                assert len(sess.inflight) <= 3  # capped
            # now ack everything we receive; promotions must drain the lot
            got = set()
            s.settimeout(5.0)
            deadline = time.time() + 10
            while len(got) < n and time.time() < deadline:
                ptype, flags, body = m._read_packet(s)
                if ptype != m.PUBLISH:
                    continue
                _, payload, pid = m._parse_publish(flags, body)
                got.add(payload)
                if pid is not None:
                    s.sendall(
                        bytes([m.PUBACK << 4, 2]) + _struct.pack(">H", pid))
            assert got == {f"p{i}".encode() for i in range(n)}
            tx.close()
            s.close()
        finally:
            broker.close()

    def test_killed_subscriber_reconnects_without_loss(self):
        """The end-to-end at-least-once contract across a flaky
        subscriber link: kill the subscriber (no DISCONNECT) mid-stream,
        keep publishing, reconnect with the same client id — every
        message arrives (duplicates allowed, loss not)."""
        broker = MiniBroker(retransmit_s=0.2)
        try:
            got = []
            sub = MqttClient(
                broker.host, broker.port, client_id="persist-sub",
                clean_session=False, reconnect=False,
            )
            sub.subscribe("k/t", lambda t, p: got.append(p), qos=1)
            tx = MqttClient(broker.host, broker.port, client_id="pub")
            time.sleep(0.1)
            tx.publish("k/t", b"m0", qos=1)
            deadline = time.time() + 5
            while len(got) < 1 and time.time() < deadline:
                time.sleep(0.02)
            assert got == [b"m0"]

            # hard-kill the subscriber: socket torn down, no DISCONNECT
            sub._sock.shutdown(__import__("socket").SHUT_RDWR)
            time.sleep(0.3)
            # published into the void: session queues them
            for i in range(1, 6):
                tx.publish("k/t", f"m{i}".encode(), qos=1)
            assert tx.drain(5) == 0  # broker acked the publisher

            # same client id, persistent session -> queued messages land
            sub2 = MqttClient(
                broker.host, broker.port, client_id="persist-sub",
                clean_session=False,
            )
            sub2.subscribe("k/t", lambda t, p: got.append(p), qos=1)
            want = {f"m{i}".encode() for i in range(6)}
            deadline = time.time() + 10
            while not want.issubset(set(got)) and time.time() < deadline:
                time.sleep(0.05)
            assert want.issubset(set(got)), f"lost: {want - set(got)}"
            # post-reconnect stream continues
            tx.publish("k/t", b"m6", qos=1)
            deadline = time.time() + 5
            while b"m6" not in got and time.time() < deadline:
                time.sleep(0.02)
            assert b"m6" in got
            tx.close(); sub.close(); sub2.close()
        finally:
            broker.close()


class TestBrokerRestart:
    def test_reconnect_resubscribe_and_redeliver(self):
        """Kill the broker mid-stream; the client reconnects, re-subscribes,
        and unacked QoS-1 publishes are redelivered (at-least-once, no
        corruption) — the reference mqttsrc.c reconnect contract."""
        b1 = MiniBroker()
        port = b1.port
        got = []
        rx = MqttClient(b1.host, port, client_id="rx")
        rx.subscribe("s/#", lambda t, p: got.append(p))
        tx = MqttClient(b1.host, port, client_id="tx", retransmit_s=0.3,
                        reconnect_delay_s=1.0)  # publisher lags subscriber
        time.sleep(0.1)
        tx.publish("s/a", b"before", qos=1)
        deadline = time.time() + 5
        while len(got) < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert got == [b"before"]

        b1.close()  # broker dies mid-stream
        time.sleep(0.2)
        # published while down: parked as unacked QoS-1
        tx.publish("s/a", b"during", qos=1)
        assert tx.unacked() >= 1

        b2 = _restart_broker(port)  # broker comes back on the same port
        try:
            deadline = time.time() + 10
            while (tx.unacked() or b"during" not in got) and time.time() < deadline:
                time.sleep(0.05)
            assert tx.unacked() == 0
            assert b"during" in got  # redelivered through the new broker
            # stream resumes normally (rx auto-resubscribed)
            tx.publish("s/a", b"after", qos=1)
            deadline = time.time() + 5
            while b"after" not in got and time.time() < deadline:
                time.sleep(0.02)
            assert b"after" in got
        finally:
            tx.close(); rx.close(); b2.close()

    def test_element_stream_survives_restart(self):
        """mqttsink qos=1 -> broker restart -> mqttsrc: frames resume,
        every delivered frame decodes (no corruption)."""
        b1 = MiniBroker()
        port = b1.port
        rx = parse_pipeline(
            f"mqttsrc host=127.0.0.1 port={port} sub-topic=el/t "
            "sub-timeout=15000 num-buffers=3 ! tensor_sink name=out"
        )
        rx.start()
        tx = parse_pipeline(
            f"appsrc name=src ! mqttsink host=127.0.0.1 port={port} "
            "pub-topic=el/t qos=1"
        )
        tx.start()
        time.sleep(0.2)
        tx["src"].push(np.int32([1]))
        time.sleep(0.3)
        b1.close()  # mid-stream broker death
        time.sleep(0.2)
        tx["src"].push(np.int32([2]))  # parked unacked
        b2 = _restart_broker(port)
        try:
            time.sleep(0.5)
            tx["src"].push(np.int32([3]))
            rx.wait(timeout=20)
            frames = rx["out"].frames
            rx.stop()
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()
            vals = [int(np.asarray(f.tensors[0])[0]) for f in frames]
            # at-least-once: 2 and 3 must arrive post-restart; every frame
            # decoded cleanly (wire errors would have dropped them)
            assert 2 in vals and 3 in vals
        finally:
            b2.close()


def test_broker_restart_preserves_acked_undelivered():
    """Messages the broker PUBACKed but had not delivered survive a
    broker kill + rebind on the same port (the persistence the
    at-least-once chain needs end-to-end; found by the 20-min soak)."""
    b1 = MiniBroker(retransmit_s=0.2)
    port = b1.port
    # persistent subscriber establishes the session, then goes offline
    sub = MqttClient("127.0.0.1", port, client_id="persist-sub",
                     clean_session=False)
    got = []
    sub.subscribe("p/t", lambda t, m: got.append(bytes(m)), qos=1)
    time.sleep(0.2)
    sub.close()
    time.sleep(0.1)

    # publisher: messages are acked by the broker, queued for the
    # offline subscriber
    pub = MqttClient("127.0.0.1", port, client_id="persist-pub")
    for i in range(5):
        pub.publish("p/t", f"m{i}".encode(), qos=1)
    assert pub.drain(5.0) == 0  # broker acked everything
    pub.close()

    # chaos: broker dies holding the backlog; a successor rebinds
    b1.close()
    deadline = time.time() + 8
    b2 = None
    while b2 is None:
        try:
            b2 = MiniBroker(port=port, retransmit_s=0.2)
        except OSError:
            assert time.time() < deadline
            time.sleep(0.1)

    # subscriber returns: the acked backlog must arrive
    sub2 = MqttClient("127.0.0.1", port, client_id="persist-sub",
                      clean_session=False)
    sub2.subscribe("p/t", lambda t, m: got.append(bytes(m)), qos=1)
    deadline = time.time() + 10
    while len(got) < 5 and time.time() < deadline:
        time.sleep(0.05)
    sub2.close()
    b2.close()
    assert sorted(set(got)) == [f"m{i}".encode() for i in range(5)], got
