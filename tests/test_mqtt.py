"""MQTT transport (mini client/broker) + mqttsink/mqttsrc elements."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.distributed.mqtt import MiniBroker, MqttClient, topic_matches
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture
def broker():
    b = MiniBroker()
    yield b
    b.close()


class TestTopicMatch:
    def test_wildcards(self):
        assert topic_matches("a/b", "a/b")
        assert not topic_matches("a/b", "a/c")
        assert topic_matches("a/+", "a/b")
        assert not topic_matches("a/+", "a/b/c")
        assert topic_matches("a/#", "a/b/c")
        assert topic_matches("#", "anything/at/all")
        assert not topic_matches("a/b/c", "a/b")


class TestClientBroker:
    def test_pub_sub_roundtrip(self, broker):
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("nns/#", lambda t, p: (got.append((t, p)), ev.set()))
        time.sleep(0.05)
        pub = MqttClient(broker.host, broker.port)
        pub.publish("nns/test", b"hello")
        assert ev.wait(5)
        assert got == [("nns/test", b"hello")]
        pub.close()
        sub.close()

    def test_retained_message(self, broker):
        pub = MqttClient(broker.host, broker.port)
        pub.publish("cfg/x", b"state", retain=True)
        time.sleep(0.05)
        got = []
        ev = threading.Event()
        sub = MqttClient(broker.host, broker.port)
        sub.subscribe("cfg/+", lambda t, p: (got.append(p), ev.set()))
        assert ev.wait(5)
        assert got == [b"state"]
        pub.close()
        sub.close()

    def test_ping(self, broker):
        c = MqttClient(broker.host, broker.port)
        c.ping()  # must not raise / kill the connection
        time.sleep(0.05)
        c.publish("t", b"x")
        c.close()


class TestMqttElements:
    def test_pipeline_pubsub(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=nns/stream num-buffers=3 sub-timeout=15000 ! "
            "tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.2)  # let the subscription land

        tx = parse_pipeline(
            f"appsrc name=src ! mqttsink host={broker.host} "
            f"port={broker.port} pub-topic=nns/stream"
        )
        tx.start()
        for i in range(3):
            tx["src"].push(np.full((4,), i, np.float32), pts=i * 0.1)
        tx["src"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()

        rx.wait(timeout=30)
        rx.stop()
        frames = rx["out"].frames
        assert len(frames) == 3
        np.testing.assert_allclose(frames[1].tensors[0], np.full((4,), 1.0))
        # timestamp rebasing: sender clock mapped into receiver domain —
        # relative spacing preserved
        assert frames[1].pts - frames[0].pts == pytest.approx(0.1, abs=0.02)
        assert "mqtt-latency-s" in frames[0].meta

    def test_src_timeout_eos(self, broker):
        rx = parse_pipeline(
            f"mqttsrc host={broker.host} port={broker.port} "
            "sub-topic=never/published sub-timeout=300 ! tensor_sink name=out"
        )
        rx.start()
        rx.wait(timeout=15)  # EOS via sub-timeout
        rx.stop()
        assert rx["out"].frames == []


def _restart_broker(port, timeout=8.0):
    """Rebind the broker port, retrying while old sockets drain."""
    deadline = time.time() + timeout
    while True:
        try:
            return MiniBroker(port=port)
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)


class TestQos1:
    def test_puback_drains_pending(self, broker):
        sub_got = []
        rx = MqttClient(broker.host, broker.port)
        rx.subscribe("q/1", lambda t, p: sub_got.append(p))
        tx = MqttClient(broker.host, broker.port)
        time.sleep(0.1)
        tx.publish("q/1", b"hello", qos=1)
        deadline = time.time() + 5
        while (tx.unacked() or len(sub_got) < 1) and time.time() < deadline:
            time.sleep(0.02)
        assert tx.unacked() == 0  # PUBACK received
        assert sub_got == [b"hello"]
        tx.close(); rx.close()

    def test_qos0_unaffected(self, broker):
        tx = MqttClient(broker.host, broker.port)
        tx.publish("q/0", b"x", qos=0)
        assert tx.unacked() == 0
        tx.close()


class TestBrokerRestart:
    def test_reconnect_resubscribe_and_redeliver(self):
        """Kill the broker mid-stream; the client reconnects, re-subscribes,
        and unacked QoS-1 publishes are redelivered (at-least-once, no
        corruption) — the reference mqttsrc.c reconnect contract."""
        b1 = MiniBroker()
        port = b1.port
        got = []
        rx = MqttClient(b1.host, port, client_id="rx")
        rx.subscribe("s/#", lambda t, p: got.append(p))
        tx = MqttClient(b1.host, port, client_id="tx", retransmit_s=0.3,
                        reconnect_delay_s=1.0)  # publisher lags subscriber
        time.sleep(0.1)
        tx.publish("s/a", b"before", qos=1)
        deadline = time.time() + 5
        while len(got) < 1 and time.time() < deadline:
            time.sleep(0.02)
        assert got == [b"before"]

        b1.close()  # broker dies mid-stream
        time.sleep(0.2)
        # published while down: parked as unacked QoS-1
        tx.publish("s/a", b"during", qos=1)
        assert tx.unacked() >= 1

        b2 = _restart_broker(port)  # broker comes back on the same port
        try:
            deadline = time.time() + 10
            while (tx.unacked() or b"during" not in got) and time.time() < deadline:
                time.sleep(0.05)
            assert tx.unacked() == 0
            assert b"during" in got  # redelivered through the new broker
            # stream resumes normally (rx auto-resubscribed)
            tx.publish("s/a", b"after", qos=1)
            deadline = time.time() + 5
            while b"after" not in got and time.time() < deadline:
                time.sleep(0.02)
            assert b"after" in got
        finally:
            tx.close(); rx.close(); b2.close()

    def test_element_stream_survives_restart(self):
        """mqttsink qos=1 -> broker restart -> mqttsrc: frames resume,
        every delivered frame decodes (no corruption)."""
        b1 = MiniBroker()
        port = b1.port
        rx = parse_pipeline(
            f"mqttsrc host=127.0.0.1 port={port} sub-topic=el/t "
            "sub-timeout=15000 num-buffers=3 ! tensor_sink name=out"
        )
        rx.start()
        tx = parse_pipeline(
            f"appsrc name=src ! mqttsink host=127.0.0.1 port={port} "
            "pub-topic=el/t qos=1"
        )
        tx.start()
        time.sleep(0.2)
        tx["src"].push(np.int32([1]))
        time.sleep(0.3)
        b1.close()  # mid-stream broker death
        time.sleep(0.2)
        tx["src"].push(np.int32([2]))  # parked unacked
        b2 = _restart_broker(port)
        try:
            time.sleep(0.5)
            tx["src"].push(np.int32([3]))
            rx.wait(timeout=20)
            frames = rx["out"].frames
            rx.stop()
            tx["src"].end_of_stream()
            tx.wait(timeout=10)
            tx.stop()
            vals = [int(np.asarray(f.tensors[0])[0]) for f in frames]
            # at-least-once: 2 and 3 must arrive post-restart; every frame
            # decoded cleanly (wire errors would have dropped them)
            assert 2 in vals and 3 in vals
        finally:
            b2.close()
