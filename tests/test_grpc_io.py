"""tensor_src_grpc / tensor_sink_grpc — one-way tensor pipes over gRPC."""

import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline


class TestSendDirection:
    def test_sink_client_to_src_server(self):
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=3 "
            "timeout=15000 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        assert port

        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port}"
        )
        tx.start()
        for i in range(3):
            tx["a"].push(np.full((2, 2), i, np.int32), pts=i * 0.5)
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()

        rx.wait(timeout=30)
        rx.stop()
        frames = rx["out"].frames
        assert len(frames) == 3
        np.testing.assert_array_equal(
            frames[2].tensors[0], np.full((2, 2), 2, np.int32)
        )
        assert frames[1].pts == pytest.approx(0.5)


class TestPullDirection:
    def test_src_client_pulls_from_sink_server(self):
        tx = parse_pipeline(
            "appsrc name=a ! tensor_sink_grpc name=s server=true port=0"
        )
        tx.start()
        port = tx["s"].bound_port
        assert port

        rx = parse_pipeline(
            f"tensor_src_grpc server=false port={port} num-buffers=2 ! "
            "tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.2)  # let the Pull stream attach
        for i in range(2):
            tx["a"].push(np.float32([i, i + 1]))
        rx.wait(timeout=30)
        rx.stop()
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()
        frames = rx["out"].frames
        assert len(frames) == 2
        np.testing.assert_allclose(frames[1].tensors[0], [1.0, 2.0])

    def test_src_server_timeout_eos(self):
        rx = parse_pipeline(
            "tensor_src_grpc server=true port=0 timeout=300 ! "
            "tensor_sink name=out"
        )
        rx.start()
        rx.wait(timeout=15)
        rx.stop()
        assert rx["out"].frames == []


class TestServerRestartMidStream:
    def test_pull_client_survives_server_restart(self):
        """GrpcSrc (client) keeps pulling after its peer server pipeline is
        stopped and a new one starts on the same port (VERDICT item 10)."""
        tx1 = parse_pipeline(
            "appsrc name=a ! tensor_sink_grpc name=s server=true port=0"
        )
        tx1.start()
        port = tx1["s"].bound_port

        rx = parse_pipeline(
            f"tensor_src_grpc server=false port={port} num-buffers=4 "
            "timeout=20000 ! tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.3)
        tx1["a"].push(np.int32([1]))
        tx1["a"].push(np.int32([2]))
        time.sleep(0.5)
        tx1.stop()  # server dies mid-stream

        # new server pipeline on the SAME port
        deadline = time.time() + 8
        tx2 = None
        while tx2 is None:
            try:
                tx2 = parse_pipeline(
                    f"appsrc name=a ! tensor_sink_grpc name=s server=true port={port}"
                )
                tx2.start()
            except Exception:
                tx2 = None
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        time.sleep(0.8)  # let the client's pull reconnect
        tx2["a"].push(np.int32([3]))
        tx2["a"].push(np.int32([4]))
        rx.wait(timeout=30)
        frames = rx["out"].frames
        rx.stop()
        tx2.stop()
        vals = [int(np.asarray(f.tensors[0])[0]) for f in frames]
        assert 3 in vals and 4 in vals  # post-restart frames flowed

    def test_send_client_retries_through_restart(self):
        """GrpcSink (client) retries Sends while its peer server restarts."""
        rx1 = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=3 "
            "timeout=20000 ! tensor_sink name=out"
        )
        rx1.start()
        port = rx1["src"].bound_port
        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port} "
            "retry-timeout=15"
        )
        tx.start()
        tx["a"].push(np.int32([1]))
        time.sleep(0.4)

        # kill and restart the receiving server on the same port;
        # NOTE rx1 received 1 frame already, rx2 expects the remaining 2
        rx1.stop()
        frames1 = rx1["out"].frames
        time.sleep(0.3)
        tx["a"].push(np.int32([2]))  # lands in the retry loop
        deadline = time.time() + 8
        rx2 = None
        while rx2 is None:
            try:
                rx2 = parse_pipeline(
                    f"tensor_src_grpc name=src server=true port={port} "
                    "num-buffers=2 timeout=20000 ! tensor_sink name=out"
                )
                rx2.start()
            except Exception:
                rx2 = None
                if time.time() > deadline:
                    raise
                time.sleep(0.2)
        tx["a"].push(np.int32([3]))
        rx2.wait(timeout=30)
        frames2 = rx2["out"].frames
        rx2.stop()
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()
        vals = [int(np.asarray(f.tensors[0])[0]) for f in frames1 + frames2]
        assert 2 in vals and 3 in vals
