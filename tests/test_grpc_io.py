"""tensor_src_grpc / tensor_sink_grpc — one-way tensor pipes over gRPC."""

import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline


class TestSendDirection:
    def test_sink_client_to_src_server(self):
        rx = parse_pipeline(
            "tensor_src_grpc name=src server=true port=0 num-buffers=3 "
            "timeout=15000 ! tensor_sink name=out"
        )
        rx.start()
        port = rx["src"].bound_port
        assert port

        tx = parse_pipeline(
            f"appsrc name=a ! tensor_sink_grpc server=false port={port}"
        )
        tx.start()
        for i in range(3):
            tx["a"].push(np.full((2, 2), i, np.int32), pts=i * 0.5)
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()

        rx.wait(timeout=30)
        rx.stop()
        frames = rx["out"].frames
        assert len(frames) == 3
        np.testing.assert_array_equal(
            frames[2].tensors[0], np.full((2, 2), 2, np.int32)
        )
        assert frames[1].pts == pytest.approx(0.5)


class TestPullDirection:
    def test_src_client_pulls_from_sink_server(self):
        tx = parse_pipeline(
            "appsrc name=a ! tensor_sink_grpc name=s server=true port=0"
        )
        tx.start()
        port = tx["s"].bound_port
        assert port

        rx = parse_pipeline(
            f"tensor_src_grpc server=false port={port} num-buffers=2 ! "
            "tensor_sink name=out"
        )
        rx.start()
        time.sleep(0.2)  # let the Pull stream attach
        for i in range(2):
            tx["a"].push(np.float32([i, i + 1]))
        rx.wait(timeout=30)
        rx.stop()
        tx["a"].end_of_stream()
        tx.wait(timeout=15)
        tx.stop()
        frames = rx["out"].frames
        assert len(frames) == 2
        np.testing.assert_allclose(frames[1].tensors[0], [1.0, 2.0])

    def test_src_server_timeout_eos(self):
        rx = parse_pipeline(
            "tensor_src_grpc server=true port=0 timeout=300 ! "
            "tensor_sink name=out"
        )
        rx.start()
        rx.wait(timeout=15)
        rx.stop()
        assert rx["out"].frames == []
