"""KV-cache autoregressive generation (transformer zoo ``generate:<N>``).

Oracle: greedy decoding with the full (no-cache) forward re-run per token
must produce the same tokens as the single-scan KV-cache program — the
cache path is a pure optimization, never a semantic change.

Reference analog: recurrence is emulated by looping frames through
tensor_repo (``tests/nnstreamer_repo_lstm``); here the loop is one
compiled XLA scan.
"""

import jax
import numpy as np
import pytest

from nnstreamer_tpu.elements.filter import SingleShot
from nnstreamer_tpu.models import build
from nnstreamer_tpu.pipeline import parse_pipeline

PROPS = {
    "dtype": "float32", "vocab": 61, "d_model": 32, "heads": 2,
    "layers": 2, "d_ff": 64, "seq": 32, "seed": 11,
}
CUSTOM = "arch:transformer," + ",".join(
    f"{k}:{v}" for k, v in PROPS.items()
)


def _greedy_oracle(fn_full, params, prompt, n):
    seq = prompt.copy()
    for _ in range(n):
        logits = np.asarray(fn_full(params, [seq])[0])
        nxt = np.argmax(logits[:, -1, :], axis=-1).astype(np.int32)
        seq = np.concatenate([seq, nxt[:, None]], axis=1)
    return seq


@pytest.mark.slow  # tier-1 budget: ~31s O(T^2) re-forward oracle; greedy
# correctness stays tier-1 via singleshot-vs-pipeline parity and the
# streaming/slotted bit-parity chain rooted at the same generate() path
def test_generate_matches_full_forward_oracle(rng):
    n_new = 5
    fn_gen, params, _, _ = build(
        "transformer", {**PROPS, "generate": str(n_new)}
    )
    fn_full, params_full, _, _ = build("transformer", PROPS)
    # same seed/arch -> identical params
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params_full)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    prompt = rng.integers(0, PROPS["vocab"], (2, 7)).astype(np.int32)
    got = np.asarray(jax.jit(lambda p, x: fn_gen(p, [x])[0])(params, prompt))
    want = _greedy_oracle(fn_full, params_full, prompt, n_new)
    assert got.shape == (2, 7 + n_new)
    np.testing.assert_array_equal(got[:, :7], prompt)
    np.testing.assert_array_equal(got, want)


def test_generate_singleshot_and_pipeline(rng):
    """Generation served through tensor_filter: one prompt frame in, one
    completed-sequence frame out (micro-batched across prompts)."""
    prompts = [
        rng.integers(0, PROPS["vocab"], (6,)).astype(np.int32)
        for _ in range(5)
    ]
    with SingleShot(
        framework="jax-xla", model="zoo", custom=CUSTOM + ",generate:4"
    ) as s:
        single = np.asarray(s.invoke([prompts[0]])[0])
    assert single.shape == (10,)

    pipe = parse_pipeline(
        "appsrc name=src ! "
        f"tensor_filter framework=jax-xla model=zoo "
        f"custom={CUSTOM},generate:4 max-batch=4 batch-timeout=50 ! "
        "tensor_sink name=out",
        name="llm-serve",
    )
    pipe.start()
    for p in prompts:
        pipe["src"].push(p)
    pipe["src"].end_of_stream()
    pipe.wait(timeout=120)
    outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
    pipe.stop()
    assert len(outs) == 5
    for p, o in zip(prompts, outs):
        assert o.shape == (10,)
        np.testing.assert_array_equal(o[:6], p)
    # pipeline path agrees with the pipeline-less SingleShot path
    np.testing.assert_array_equal(outs[0], single)


def test_chunked_prefill_logits_match_full_forward(rng):
    """Decode-mode prefill (one causal pass filling the K/V cache) must
    produce the same logits at every position as the ordinary forward."""
    import jax.numpy as jnp

    from nnstreamer_tpu.models._init_util import host_init
    from nnstreamer_tpu.models.transformer import (
        TransformerLM,
        _cfg_from_props,
    )

    cfg = _cfg_from_props({k: str(v) for k, v in PROPS.items()})
    full = TransformerLM(cfg)
    params = host_init(full.init, 11, np.zeros((1, 8), np.int32))
    dec = TransformerLM(cfg, decode=True)
    prompt = rng.integers(0, PROPS["vocab"], (2, 9)).astype(np.int32)

    want = np.asarray(full.apply(params, jnp.asarray(prompt)))
    cache0 = jax.tree.map(
        jnp.zeros_like,
        dec.init(jax.random.PRNGKey(0), jnp.zeros((2, 1), jnp.int32))[
            "cache"
        ],
    )
    got, _ = dec.apply(
        {"params": params["params"], "cache": cache0},
        jnp.asarray(prompt),
        mutable=["cache"],
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_quantized_generation_runs(rng):
    """quantize:int8 composes with generate:<N> (int8 dense layers inside
    the KV-cache scan): same weights as float, valid token stream out."""
    fn_q, p_q, _, _ = build(
        "transformer", {**PROPS, "generate": "4", "quantize": "int8"}
    )
    fn_f, p_f, _, _ = build("transformer", PROPS)
    for a, b in zip(jax.tree.leaves(p_q), jax.tree.leaves(p_f)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    prompt = rng.integers(0, PROPS["vocab"], (2, 6)).astype(np.int32)
    out = np.asarray(jax.jit(lambda p, x: fn_q(p, [x])[0])(p_q, prompt))
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(out[:, :6], prompt)
    assert ((out >= 0) & (out < PROPS["vocab"])).all()


@pytest.mark.slow  # tier-1 budget: ~23s; seeded-sampling determinism stays
# tier-1 via slotted sampling parity and the seeded prefix warm-hit pin,
# which both re-run this path and compare it against an independent engine
def test_sampled_generation_deterministic_and_topk_bounded(rng):
    """temperature/top_k sampling: deterministic per gen_seed, different
    seeds diverge, and top_k=1 degenerates to greedy."""
    base = {**PROPS, "generate": "6", "temperature": "1.0", "top_k": "5"}
    prompt = rng.integers(0, PROPS["vocab"], (2, 5)).astype(np.int32)

    f1, p1, _, _ = build("transformer", {**base, "gen_seed": "1"})
    f1b, p1b, _, _ = build("transformer", {**base, "gen_seed": "1"})
    f2, p2, _, _ = build("transformer", {**base, "gen_seed": "2"})
    a = np.asarray(f1(p1, [prompt])[0])
    b = np.asarray(f1b(p1b, [prompt])[0])
    c = np.asarray(f2(p2, [prompt])[0])
    np.testing.assert_array_equal(a, b)  # same seed -> same stream
    assert not np.array_equal(a, c)  # different seed -> diverges
    assert ((a >= 0) & (a < PROPS["vocab"])).all()

    # top_k=1 at any temperature IS greedy
    fk, pk, _, _ = build(
        "transformer",
        {**PROPS, "generate": "6", "temperature": "0.7", "top_k": "1"},
    )
    fg, pg, _, _ = build("transformer", {**PROPS, "generate": "6"})
    np.testing.assert_array_equal(
        np.asarray(fk(pk, [prompt])[0]), np.asarray(fg(pg, [prompt])[0])
    )


def test_generate_rejects_overflow(rng):
    fn_gen, params, _, _ = build(
        "transformer", {**PROPS, "generate": "30"}
    )
    prompt = rng.integers(0, PROPS["vocab"], (1, 8)).astype(np.int32)
    try:
        fn_gen(params, [prompt])
    except ValueError as e:
        assert "max_seq" in str(e)
    else:
        raise AssertionError("expected ValueError for seq overflow")
