"""Real media ingest: y4m/wav/text/octet file sources -> tensor_converter.

SSAT-style golden tests (≙ reference runTest.sh pipelines that push real
media files through tensor_converter and byte-compare the output against
directly-computed goldens; converter framing semantics:
gst/nnstreamer/elements/gsttensor_converter.c:750-1005).
"""

import numpy as np
import pytest

from nnstreamer_tpu.media.caps import MediaSpec, parse_media_caps, round_up_4
from nnstreamer_tpu.media.wav import read_wav, write_wav
from nnstreamer_tpu.media.y4m import Y4MReader, i420_to_rgb, rgb_to_i420, write_y4m
from nnstreamer_tpu.pipeline import parse_pipeline


def _run(pipeline_text, timeout=60):
    pipe = parse_pipeline(pipeline_text)
    pipe.start()
    pipe.wait(timeout=timeout)
    frames = list(pipe["out"].frames)
    pipe.stop()
    return frames, pipe


def _blocky_rgb(h, w, seed=0, n=3):
    """2x2-aligned random blocks: survives I420 chroma subsampling with
    small, bounded error (sharp sub-2px detail would not)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        small = rng.integers(0, 256, (h // 2, w // 2, 3), dtype=np.uint8)
        out.append(np.repeat(np.repeat(small, 2, axis=0), 2, axis=1))
    return out


class TestContainers:
    def test_y4m_roundtrip_close(self, tmp_path):
        frames = _blocky_rgb(16, 12)
        path = str(tmp_path / "t.y4m")
        write_y4m(path, frames, framerate=__import__("fractions").Fraction(25, 1))
        with Y4MReader(path) as r:
            assert (r.width, r.height) == (12, 16)
            assert r.framerate == __import__("fractions").Fraction(25, 1)
            got = list(r.frames_rgb())
        assert len(got) == 3
        for a, b in zip(frames, got):
            # limited-range quantization + rounding: small bounded error
            assert np.max(np.abs(a.astype(int) - b.astype(int))) <= 12
            assert np.mean(np.abs(a.astype(int) - b.astype(int))) <= 3

    def test_yuv_rgb_inverse_on_primaries(self):
        # black, white, mid-gray: luma-only, chroma-neutral -> near-exact
        for val in (0, 128, 255):
            img = np.full((4, 4, 3), val, np.uint8)
            y, u, v = rgb_to_i420(img)
            back = i420_to_rgb(y, u, v)
            assert np.max(np.abs(back.astype(int) - val)) <= 3

    def test_wav_roundtrip_exact(self, tmp_path):
        t = np.arange(2000, dtype=np.float32)
        stereo = np.stack(
            [np.sin(t / 10) * 20000, np.cos(t / 7) * 15000], axis=1
        ).astype(np.int16)
        path = str(tmp_path / "t.wav")
        write_wav(path, stereo, rate=16000)
        rate, channels, fmt, data = read_wav(path)
        assert (rate, channels, fmt) == (16000, 2, "S16LE")
        np.testing.assert_array_equal(data, stereo)

    def test_media_caps_parse(self):
        m = parse_media_caps("video/x-raw,format=RGB,width=6,height=4,framerate=30/1")
        assert (m.mtype, m.format, m.width, m.height) == ("video", "RGB", 6, 4)
        assert m.stride == round_up_4(18) == 20  # rows padded to 4 bytes
        a = parse_media_caps("audio/x-raw,format=S16LE,rate=16000,channels=2")
        assert (a.mtype, a.rate, a.channels) == ("audio", 16000, 2)
        assert MediaSpec(media=m).intersect(MediaSpec(media=m)).media == m
        assert MediaSpec(media=m).intersect(MediaSpec(media=a)) is None


class TestVideoIngest:
    def test_stride_removal_golden(self, tmp_path):
        # width 6 -> row bytes 18, stride 20: the exact misalignment case
        # the reference strips per-row (gsttensor_converter.c video chain)
        frames = _blocky_rgb(4, 6, seed=1)
        path = str(tmp_path / "s.y4m")
        write_y4m(path, frames)
        with Y4MReader(path) as r:
            golden = list(r.frames_rgb())  # oracle: reader output, unpadded
        got, pipe = _run(
            f"videofilesrc location={path} ! tensor_converter ! "
            "tensor_sink name=out"
        )
        assert len(got) == 3
        for f, g in zip(got, golden):
            assert f.tensors[0].shape == (4, 6, 3)
            np.testing.assert_array_equal(f.tensors[0], g)
            assert "media" not in f.meta  # converted: no longer raw media

    def test_static_negotiation_from_media_caps(self, tmp_path):
        path = str(tmp_path / "n.y4m")
        write_y4m(path, _blocky_rgb(8, 6))
        pipe = parse_pipeline(
            f"videofilesrc location={path} name=src ! "
            "tensor_converter name=c ! tensor_sink name=out"
        )
        pipe.start()
        # converter derived the exact static schema BEFORE any data flowed
        spec = pipe["c"].srcpads[0].spec
        assert spec.is_static
        assert spec.tensors[0].shape == (8, 6, 3)
        assert str(spec.tensors[0].dtype) == "uint8"
        pipe.wait(timeout=60)
        pipe.stop()

    @pytest.mark.parametrize("fmt,channels", [("BGRx", 4), ("GRAY8", 1)])
    def test_formats(self, tmp_path, fmt, channels):
        frames = _blocky_rgb(4, 6, seed=2)
        path = str(tmp_path / "f.y4m")
        write_y4m(path, frames)
        got, _ = _run(
            f"videofilesrc location={path} format={fmt} ! "
            "tensor_converter ! tensor_sink name=out"
        )
        assert got and got[0].tensors[0].shape == (4, 6, channels)
        if fmt == "BGRx":
            with Y4MReader(path) as r:
                rgb = next(r.frames_rgb())
            np.testing.assert_array_equal(got[0].tensors[0][..., :3], rgb[..., ::-1])
            assert (got[0].tensors[0][..., 3] == 255).all()

    def test_frames_per_tensor_batching(self, tmp_path):
        path = str(tmp_path / "b.y4m")
        write_y4m(path, _blocky_rgb(4, 4, n=4))
        got, _ = _run(
            f"videofilesrc location={path} ! "
            "tensor_converter frames-per-tensor=2 ! tensor_sink name=out"
        )
        # 4 media frames -> 2 batched tensors (N,H,W,C)
        assert len(got) == 2
        assert got[0].tensors[0].shape == (2, 4, 4, 3)

    def test_num_buffers_limit(self, tmp_path):
        path = str(tmp_path / "l.y4m")
        write_y4m(path, _blocky_rgb(4, 4, n=5))
        got, _ = _run(
            f"videofilesrc location={path} num-buffers=2 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        assert len(got) == 2


class TestAudioIngest:
    def test_wav_to_tensors_golden(self, tmp_path):
        t = np.arange(4096, dtype=np.float32)
        stereo = np.stack(
            [np.sin(t / 9) * 12000, np.sin(t / 5) * 9000], axis=1
        ).astype(np.int16)
        path = str(tmp_path / "a.wav")
        write_wav(path, stereo, rate=8000)
        got, _ = _run(
            f"audiofilesrc location={path} samples-per-buffer=512 ! "
            "tensor_converter ! tensor_sink name=out"
        )
        assert len(got) == 8  # 4096 / 512
        for i, f in enumerate(got):
            assert f.tensors[0].shape == (512, 2)
            assert f.tensors[0].dtype == np.int16
            np.testing.assert_array_equal(
                f.tensors[0], stereo[i * 512 : (i + 1) * 512]
            )

    def test_audio_static_negotiation(self, tmp_path):
        path = str(tmp_path / "a8.wav")
        write_wav(path, np.zeros(1024, np.uint8), rate=8000)
        pipe = parse_pipeline(
            f"audiofilesrc location={path} samples-per-buffer=256 ! "
            "tensor_converter name=c ! tensor_sink name=out"
        )
        pipe.start()
        spec = pipe["c"].srcpads[0].spec
        assert spec.is_static and spec.tensors[0].shape == (256, 1)
        pipe.wait(timeout=30)
        pipe.stop()


class TestTextOctetIngest:
    def test_text_fixed_framing(self, tmp_path):
        path = str(tmp_path / "t.txt")
        path_obj = tmp_path / "t.txt"
        path_obj.write_bytes(b"hello\nworld-is-long\nx\n")
        got, _ = _run(
            f"textfilesrc location={path} ! "
            "tensor_converter input-dim=8 input-type=uint8 ! "
            "tensor_sink name=out"
        )
        assert len(got) == 3
        # pad with NUL / truncate to input-dim bytes (reference text chain)
        assert bytes(got[0].tensors[0]) == b"hello\x00\x00\x00"
        assert bytes(got[1].tensors[0]) == b"world-is"
        assert bytes(got[2].tensors[0]) == b"x" + b"\x00" * 7

    def test_octet_typed_reshape(self, tmp_path):
        data = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        path = tmp_path / "o.bin"
        path.write_bytes(data.tobytes())
        # reference dialect is innermost-first: 4:3 -> numpy (3, 4)
        got, _ = _run(
            f"filesrc location={path} blocksize={4 * 12} ! "
            "tensor_converter input-dim=4:3 input-type=float32 ! "
            "tensor_sink name=out"
        )
        assert len(got) == 2
        np.testing.assert_array_equal(got[0].tensors[0], data[0])
        np.testing.assert_array_equal(got[1].tensors[0], data[1])

    def test_octet_size_mismatch_errors(self, tmp_path):
        path = tmp_path / "o.bin"
        path.write_bytes(b"\x00" * 10)
        pipe = parse_pipeline(
            f"filesrc location={path} blocksize=10 ! "
            "tensor_converter input-dim=3:4 input-type=float32 ! "
            "tensor_sink name=out"
        )
        pipe.start()
        with pytest.raises(Exception, match="octet payload"):
            pipe.wait(timeout=30)
        pipe.stop()


class TestMediaToModel:
    def test_video_file_through_filter(self, tmp_path):
        """Reference example-pipeline shape: media file -> converter ->
        transform -> filter -> sink, end to end with a real file."""
        from nnstreamer_tpu.backends import (
            register_custom_easy,
            unregister_custom_easy,
        )

        path = str(tmp_path / "m.y4m")
        write_y4m(path, _blocky_rgb(8, 8, n=2))
        register_custom_easy(
            "brightsum",
            lambda xs: [np.asarray([np.asarray(xs[0]).sum()], np.int64)],
        )
        try:
            got, _ = _run(
                f"videofilesrc location={path} ! tensor_converter ! "
                "tensor_transform mode=typecast option=int64 ! "
                "tensor_filter framework=custom-easy model=brightsum ! "
                "tensor_sink name=out"
            )
        finally:
            unregister_custom_easy("brightsum")
        with Y4MReader(path) as r:
            golden = [int(f.astype(np.int64).sum()) for f in r.frames_rgb()]
        assert [int(f.tensors[0][0]) for f in got] == golden


class TestImageIngest:
    def _write_pngs(self, tmp_path, n=4, size=(6, 8)):
        from nnstreamer_tpu.media.image import write_image

        rng = np.random.default_rng(3)
        paths, imgs = [], []
        for i in range(n):
            img = rng.integers(0, 255, (*size, 3), np.uint8)
            p = str(tmp_path / f"img_{i:02d}.png")
            write_image(p, img)
            paths.append(p)
            imgs.append(img)
        return paths, imgs

    def test_image_codec_roundtrip(self, tmp_path):
        from nnstreamer_tpu.media.image import read_image, write_image

        img = np.random.default_rng(0).integers(0, 255, (5, 7, 3), np.uint8)
        p = str(tmp_path / "x.png")
        write_image(p, img)
        np.testing.assert_array_equal(read_image(p), img)  # png = lossless
        gray = read_image(p, "GRAY8")
        assert gray.shape == (5, 7, 1)

    def test_imagefilesrc_glob_through_converter(self, tmp_path):
        _, imgs = self._write_pngs(tmp_path)
        pipe = parse_pipeline(
            f"imagefilesrc location={tmp_path}/img_*.png ! "
            "tensor_converter ! tensor_sink name=out"
        )
        pipe.run(timeout=30)
        outs = [np.asarray(f.tensors[0]) for f in pipe["out"].frames]
        assert len(outs) == len(imgs)
        for got, want in zip(outs, imgs):
            np.testing.assert_array_equal(got, want)

    def test_imagefilesrc_rejects_mixed_sizes(self, tmp_path):
        from nnstreamer_tpu.media.image import write_image

        write_image(str(tmp_path / "a.png"), np.zeros((4, 4, 3), np.uint8))
        write_image(str(tmp_path / "b.png"), np.zeros((5, 4, 3), np.uint8))
        pipe = parse_pipeline(
            f"imagefilesrc location={tmp_path}/*.png ! tensor_sink name=out"
        )
        pipe.start()
        with pytest.raises(Exception):
            pipe.wait(timeout=20)
        pipe.stop()

    def test_datarepo_image_roundtrip(self, tmp_path):
        from nnstreamer_tpu.pipeline import parse_pipeline as pp

        # write: appsrc -> datareposink (image mode via % pattern)
        sink_pipe = pp(
            f"appsrc name=src ! datareposink "
            f"location={tmp_path}/s_%03d.png json={tmp_path}/meta.json"
        )
        sink_pipe.start()
        rng = np.random.default_rng(9)
        imgs = [rng.integers(0, 255, (6, 6, 3), np.uint8) for _ in range(5)]
        for img in imgs:
            sink_pipe["src"].push(img)
        sink_pipe["src"].end_of_stream()
        sink_pipe.wait(timeout=20)
        sink_pipe.stop()

        # read back a sub-range, shuffled deterministically
        src_pipe = pp(
            f"datareposrc location={tmp_path}/s_%03d.png "
            f"json={tmp_path}/meta.json start-sample-index=1 "
            "stop-sample-index=3 is-shuffle=true shuffle-seed=4 ! "
            "tensor_sink name=out"
        )
        src_pipe.run(timeout=30)
        got = {
            f.meta["sample_index"]: np.asarray(f.tensors[0])
            for f in src_pipe["out"].frames
        }
        assert sorted(got) == [1, 2, 3]
        for idx, arr in got.items():
            np.testing.assert_array_equal(arr, imgs[idx])

    def test_datarepo_image_sink_rejects_drifting_schema(self, tmp_path):
        from nnstreamer_tpu.elements.datarepo import DataRepoSink
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.pipeline.element import ElementError

        sink = DataRepoSink()
        sink.props["location"] = str(tmp_path / "s_%03d.png")
        sink.props["json"] = str(tmp_path / "m.json")
        sink.start()
        sink.render(TensorFrame([np.zeros((6, 6, 3), np.uint8)]))
        with pytest.raises(ElementError, match="differs"):
            sink.render(TensorFrame([np.zeros((8, 8, 3), np.uint8)]))
        with pytest.raises(ElementError, match="uint8"):
            sink.render(TensorFrame([np.zeros((6, 6), np.uint8)]))  # 2-D

    def test_datarepo_literal_percent_stays_flat(self, tmp_path):
        from nnstreamer_tpu.elements.datarepo import DataRepoSink
        from nnstreamer_tpu.core.buffer import TensorFrame

        sink = DataRepoSink()
        sink.props["location"] = str(tmp_path / "data_50%.bin")
        sink.props["json"] = str(tmp_path / "m.json")
        sink.start()
        sink.render(TensorFrame([np.ones((4,), np.float32)]))
        sink.stop()
        import json as _json
        meta = _json.load(open(tmp_path / "m.json"))
        assert meta["format"] == "static" and meta["total_samples"] == 1

    def test_printf_length_modifiers_accepted(self, tmp_path):
        # gstdatareposrc.c documents 'image_%02ld.png' / '%04lld'; these
        # must route to image mode and format like plain %d
        from nnstreamer_tpu.elements.datarepo import (
            _fmt_sample_path, _is_image_pattern,
        )

        for pat, idx, want in [
            ("img_%02ld.png", 3, "img_03.png"),
            ("img_%04lld.png", 7, "img_0007.png"),
            ("img_%lld.png", 12, "img_12.png"),
        ]:
            assert _is_image_pattern(pat)
            assert _fmt_sample_path(pat, idx) == want

    def test_imagefilesrc_printf_pattern(self, tmp_path):
        _, imgs = self._write_pngs(tmp_path)  # writes img_00..img_03
        pipe = parse_pipeline(
            f"imagefilesrc location={tmp_path}/img_%02d.png ! "
            "tensor_converter ! tensor_sink name=out"
        )
        pipe.run(timeout=30)
        assert len(pipe["out"].frames) == len(imgs)

    def test_datarepo_image_start_detects_missing_sample(self, tmp_path):
        import os as _os
        from nnstreamer_tpu.elements.datarepo import DataRepoSrc
        from nnstreamer_tpu.pipeline.element import ElementError

        self._write_pngs(tmp_path, n=3)
        import json as _json
        (tmp_path / "m.json").write_text(_json.dumps({
            "format": "image", "tensors": ["uint8:6:8:3"],
            "total_samples": 3,
        }))
        _os.remove(tmp_path / "img_01.png")
        src = DataRepoSrc()
        src.props["location"] = str(tmp_path / "img_%02d.png")
        src.props["json"] = str(tmp_path / "m.json")
        with pytest.raises(ElementError, match="missing"):
            src.start()
