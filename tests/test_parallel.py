"""Parallel subsystem tests on the virtual 8-device CPU mesh:
mesh construction, sharding rules, ring attention vs oracle, sharded
training step, graft entry points."""

import numpy as np
import pytest

import _env_capabilities

needs_spmd_stack = pytest.mark.skipif(
    not _env_capabilities.spmd_stack_ok(),
    reason="jax lacks the shard_map feature set (check_vma/pvary/pallas "
    "replication rule) the manual-SPMD stack needs",
)

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from nnstreamer_tpu.parallel import (
    make_mesh,
    ring_attention,
    reference_attention,
    shard_params,
    spec_for_path,
    transformer_rules,
)


@pytest.fixture(scope="module")
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh({"dp": 2, "sp": 4})


class TestMesh:
    def test_make_mesh_shapes(self):
        m = make_mesh({"dp": 2, "tp": 4})
        assert m.shape == {"dp": 2, "tp": 4}

    def test_wildcard_axis(self):
        m = make_mesh({"dp": -1, "tp": 2})
        assert m.shape["dp"] == 4

    def test_bad_product_n(self):
        with pytest.raises(ValueError):
            make_mesh({"dp": 3, "tp": 2})


class TestShardingRules:
    def test_rule_matching(self):
        rules = transformer_rules(tp_axis="tp")
        assert spec_for_path("params/block0/attn_qkv/kernel", rules) == P(None, "tp")
        assert spec_for_path("params/block0/attn_out/kernel", rules) == P("tp", None)
        assert spec_for_path("params/block0/mlp_up/kernel", rules) == P(None, "tp")
        assert spec_for_path("params/block1/ln1/scale", rules) == P(None)
        assert spec_for_path("params/embed/embedding", rules) == P("tp", None)

    def test_shard_params_places(self, mesh8):
        params = {"attn_qkv": {"kernel": jnp.ones((8, 16))}, "ln1": {"scale": jnp.ones(8)}}
        mesh = make_mesh({"dp": 4, "tp": 2})
        out = shard_params(params, mesh, transformer_rules())
        sh = out["attn_qkv"]["kernel"].sharding
        assert sh.spec == P(None, "tp")
        assert out["ln1"]["scale"].sharding.spec == P()

    def test_indivisible_dim_falls_back_replicated(self):
        mesh = make_mesh({"dp": 4, "tp": 2})
        params = {"attn_qkv": {"kernel": jnp.ones((8, 15))}}  # 15 % 2 != 0
        out = shard_params(params, mesh, transformer_rules())
        assert out["attn_qkv"]["kernel"].sharding.spec == P()


@needs_spmd_stack
class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, mesh8, causal):
        rng = jax.random.PRNGKey(0)
        B, T, H, D = 2, 32, 4, 16  # T sharded 4-way -> 8 per device
        q, k, v = (
            jax.random.normal(r, (B, T, H, D), jnp.float32)
            for r in jax.random.split(rng, 3)
        )
        out = ring_attention(q, k, v, mesh8, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.slow  # tier-1 budget: ~11s training-side grad compile; the
    # forward ring-vs-reference parity tests above stay tier-1
    def test_grad_flows_through_ring(self, mesh8):
        B, T, H, D = 2, 16, 2, 8
        rng = jax.random.PRNGKey(1)
        q, k, v = (
            jax.random.normal(r, (B, T, H, D), jnp.float32)
            for r in jax.random.split(rng, 3)
        )

        def loss_ring(q, k, v):
            return (ring_attention(q, k, v, mesh8, causal=True) ** 2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=True) ** 2).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_bf16_inputs(self, mesh8):
        B, T, H, D = 2, 16, 2, 8
        q = jax.random.normal(jax.random.PRNGKey(2), (B, T, H, D), jnp.bfloat16)
        out = ring_attention(q, q, q, mesh8, causal=True)
        assert out.dtype == jnp.bfloat16
        ref = reference_attention(
            q.astype(jnp.float32), q.astype(jnp.float32), q.astype(jnp.float32)
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.05
        )


class TestShardedTraining:
    def test_train_step_decreases_loss(self):
        from nnstreamer_tpu.models.transformer import (
            TransformerConfig,
            make_train_step,
        )

        mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
        cfg = TransformerConfig(
            vocab=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=32,
            dtype=jnp.float32,
        )
        step, params, opt, data_sh = make_train_step(mesh, cfg, learning_rate=1e-2)
        tokens = jax.device_put(
            jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0, 64), data_sh
        )
        losses = []
        for _ in range(5):
            params, opt, loss = step(params, opt, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # tp sharding actually applied
        qkv = params["params"]["block0"]["attn_qkv"]["kernel"]
        assert qkv.sharding.spec == P(None, "tp")


class TestGraftEntry:
    @needs_spmd_stack
    @pytest.mark.slow  # tier-1 budget: ~32s 8-way dryrun; the
    # 2/4-way entry compiles keep the graft entry covered
    def test_dryrun_multichip_8(self, capsys):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)
        assert "dryrun_multichip OK" in capsys.readouterr().out

    @pytest.mark.slow  # tier-1 budget: ~24s full graft-entry jit; the entry
    # wraps the same model forward the zoo tier-1 tests compile, so this
    # joins dryrun_multichip_8 in the full suite
    def test_entry_compiles(self):
        import __graft_entry__ as ge

        fn, args = ge.entry()
        out = jax.jit(fn)(*args)
        assert out.shape == (8, 1001)


class TestUlyssesAttention:
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern) vs the
    unsharded oracle, on the virtual 8-device CPU mesh."""

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, mesh8, causal):
        from nnstreamer_tpu.parallel.ulysses import ulysses_attention

        rng = jax.random.PRNGKey(0)
        B, T, H, D = 2, 32, 4, 16  # sp=4: T 8/device, heads 1/device
        q, k, v = (
            jax.random.normal(r, (B, T, H, D), jnp.float32)
            for r in jax.random.split(rng, 3)
        )
        out = ulysses_attention(q, k, v, mesh8, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_grad_matches_reference(self, mesh8):
        from nnstreamer_tpu.parallel.ulysses import ulysses_attention

        B, T, H, D = 2, 16, 4, 8
        rng = jax.random.PRNGKey(1)
        q, k, v = (
            jax.random.normal(r, (B, T, H, D), jnp.float32)
            for r in jax.random.split(rng, 3)
        )
        g_u = jax.grad(
            lambda *xs: (ulysses_attention(*xs, mesh8, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g_r = jax.grad(
            lambda *xs: (reference_attention(*xs, causal=True) ** 2).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g_u, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)

    def test_indivisible_heads_rejected(self, mesh8):
        from nnstreamer_tpu.parallel.ulysses import ulysses_attention

        q = jnp.zeros((2, 32, 3, 8), jnp.float32)  # 3 heads, sp=4
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q, mesh8)

    @needs_spmd_stack
    def test_auto_strategy_selection(self, mesh8):
        from nnstreamer_tpu.parallel.ulysses import sequence_attention

        rng = jax.random.PRNGKey(3)
        # divisible heads -> ulysses; indivisible -> falls back to ring —
        # both must match the oracle either way
        for H in (4, 3):
            q, k, v = (
                jax.random.normal(r, (2, 32, H, 8), jnp.float32)
                for r in jax.random.split(jax.random.fold_in(rng, H), 3)
            )
            out = sequence_attention(q, k, v, mesh8, causal=True)
            ref = reference_attention(q, k, v, causal=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @needs_spmd_stack
    def test_ring_flash_strategy(self, mesh8):
        """strategy='ring-flash': each ring hop is one Pallas kernel call
        (interpret mode on CPU), exact vs the oracle."""
        from nnstreamer_tpu.parallel.ulysses import sequence_attention

        rng = jax.random.PRNGKey(7)
        q, k, v = (
            jax.random.normal(r, (2, 32, 2, 8), jnp.float32)
            for r in jax.random.split(rng, 3)
        )
        out = sequence_attention(
            q, k, v, mesh8, causal=True, strategy="ring-flash",
            interpret=True,
        )
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)

    def test_bf16(self, mesh8):
        from nnstreamer_tpu.parallel.ulysses import ulysses_attention

        q = jax.random.normal(jax.random.PRNGKey(5), (2, 16, 4, 8), jnp.bfloat16)
        out = ulysses_attention(q, q, q, mesh8, causal=False)
        ref = reference_attention(
            q.astype(jnp.float32), q.astype(jnp.float32), q.astype(jnp.float32),
            causal=False,
        )
        np.testing.assert_allclose(
            np.asarray(out, np.float32), np.asarray(ref), atol=0.08
        )
