"""Persistent XLA compilation cache (core/compile_cache.py).

Reference analog: engine/result caching in backends (TensorRT serialized
engine cache); here compiled XLA executables persist across processes.
"""

import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_enable_creates_dir_and_sets_config(tmp_path, monkeypatch):
    from nnstreamer_tpu.core import compile_cache

    compile_cache.reset_for_tests()
    target = str(tmp_path / "xla_cache")
    monkeypatch.setenv("NNS_TPU_XLA_CACHE_DIR", target)
    from nnstreamer_tpu.core import config as nns_config

    nns_config.reset()
    import jax

    prior = jax.config.jax_compilation_cache_dir
    try:
        got = compile_cache.enable()
        # cache lives in a per-host subtree so AOT entries compiled on a
        # host with different CPU features can never be loaded here
        fp = compile_cache.host_fingerprint()
        assert got == os.path.join(target, fp)
        assert os.path.isdir(got)
        assert jax.config.jax_compilation_cache_dir == got
        # idempotent: second call returns the same dir, no re-init
        assert compile_cache.enable() == got
    finally:
        # restore the process-global flag: later tests must not write
        # cache entries into this test's doomed tmp_path
        jax.config.update("jax_compilation_cache_dir", prior)
        compile_cache.reset_for_tests()
        monkeypatch.delenv("NNS_TPU_XLA_CACHE_DIR")
        nns_config.reset()


def test_host_fingerprint_stable_and_filesystem_safe():
    from nnstreamer_tpu.core import compile_cache

    fp = compile_cache.host_fingerprint()
    assert fp == compile_cache.host_fingerprint()  # deterministic
    assert fp and "/" not in fp and not fp.startswith(".")


def test_enable_warns_on_conflicting_explicit_dir(tmp_path, caplog):
    from nnstreamer_tpu.core import compile_cache

    compile_cache.reset_for_tests()
    import jax

    prior = jax.config.jax_compilation_cache_dir
    try:
        first = compile_cache.enable(str(tmp_path / "a"))
        assert first
        import logging

        with caplog.at_level(logging.WARNING):
            again = compile_cache.enable(str(tmp_path / "b"))
        assert again == first  # sticky — but no longer silent
        assert any("already enabled" in r.message for r in caplog.records)
    finally:
        jax.config.update("jax_compilation_cache_dir", prior)
        compile_cache.reset_for_tests()


def test_cpu_platform_auto_skips_but_stays_retryable(tmp_path, monkeypatch):
    # no explicit dir + cpu platform -> no cache (XLA:CPU AOT entries log
    # feature-mismatch noise on every warm load); a later accelerator
    # open() in the same process must still be able to enable it
    from nnstreamer_tpu.core import compile_cache
    from nnstreamer_tpu.core import config as nns_config

    monkeypatch.delenv("NNS_TPU_XLA_CACHE_DIR", raising=False)
    # the auto default expands under HOME: point it at tmp_path so the
    # test neither pollutes ~/.cache nor depends on HOME being writable
    monkeypatch.setattr(
        compile_cache, "_DEFAULT_DIR", str(tmp_path / "auto_cache")
    )
    nns_config.reset()
    compile_cache.reset_for_tests()
    import jax

    prior_dir = jax.config.jax_compilation_cache_dir
    prior_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        assert compile_cache.enable(platform="cpu") is None
        got = compile_cache.enable(platform="tpu")  # retry succeeds
        assert got and compile_cache.host_fingerprint() in got
        assert got.startswith(str(tmp_path))
    finally:
        jax.config.update("jax_compilation_cache_dir", prior_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prior_min
        )
        compile_cache.reset_for_tests()
        nns_config.reset()


def test_disable_via_empty_dir(monkeypatch):
    from nnstreamer_tpu.core import compile_cache

    compile_cache.reset_for_tests()
    try:
        assert compile_cache.enable("") is None
    finally:
        compile_cache.reset_for_tests()


def test_cache_populates_across_processes(tmp_path):
    """A fresh process compiling through the jax-xla backend writes cache
    entries; a second fresh process starts with a warm cache dir."""
    cache = str(tmp_path / "xc")
    src = (
        "import os, sys, numpy as np;"
        f"sys.path.insert(0, {ROOT!r});"
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from nnstreamer_tpu.elements.filter import SingleShot;"
        "s = SingleShot(framework='jax-xla', model='zoo',"
        " custom='arch:mnist_cnn,dtype:float32');"
        "out = s.invoke_batch([np.zeros((4, 28, 28, 1), np.float32)]);"
        "s.close(); print('OK', out[0].shape)"
    )
    env = dict(
        os.environ, NNS_TPU_XLA_CACHE_DIR=cache, JAX_PLATFORMS="cpu"
    )
    r1 = subprocess.run(
        [sys.executable, "-c", src], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert r1.returncode == 0, r1.stderr[-2000:]
    entries = os.listdir(cache)
    assert entries, "first run wrote no cache entries"
    r2 = subprocess.run(
        [sys.executable, "-c", src], env=env, capture_output=True,
        text=True, timeout=240,
    )
    assert r2.returncode == 0, r2.stderr[-2000:]
