"""tflite backend: .tflite models through pipelines, on the XLA lowering.

≙ reference ``tests/nnstreamer_filter_tensorflow2_lite/runTest.sh``
(explicit framework=, framework=auto detection, single-shot invoke,
model info) — but the backend lowers the flatbuffer to JAX in-process
(``backends/tflite_import.py``); no TFLite runtime exists or is needed.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.backends.tflite_import import TFLiteBackend
from nnstreamer_tpu.elements.filter import SingleShot, detect_framework
from nnstreamer_tpu.pipeline import parse_pipeline

from test_tflite_import import (
    MOBILENET_QUANT, MODELS, build_affine_tflite, needs_ref_models)


@pytest.fixture(scope="module")
def tflite_model(tmp_path_factory):
    """y = 2x + 1 on (1, 4) float32, built with the flatbuffers Builder."""
    path = tmp_path_factory.mktemp("tfl") / "affine.tflite"
    path.write_bytes(build_affine_tflite())
    return str(path)


class TestTFLiteBackend:
    def test_pipeline_explicit_framework(self, tflite_model):
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=tflite "
            f"model={tflite_model} ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.full((1, 4), 3.0, np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        frames = pipe["out"].frames
        pipe.stop()
        np.testing.assert_allclose(
            np.asarray(frames[0].tensors[0]), np.full((1, 4), 7.0)
        )

    def test_framework_auto_detects_tflite(self, tflite_model):
        # no arch: custom prop -> jax-xla cannot load a raw .tflite, so
        # extension priority falls through to the importer backend
        assert detect_framework(tflite_model) == "tflite"

    def test_single_shot(self, tflite_model):
        with SingleShot("tflite", tflite_model) as m:
            (out,) = m.invoke([np.zeros((1, 4), np.float32)])
            np.testing.assert_allclose(np.asarray(out), np.ones((1, 4)))

    def test_model_info(self, tflite_model):
        be = TFLiteBackend()
        be.open(tflite_model, {})
        in_spec, out_spec = be.get_model_info()
        assert in_spec.tensors[0].shape == (1, 4)
        assert out_spec.tensors[0].shape == (1, 4)
        be.close()

    def test_invoke_batch_vmaps(self, tflite_model):
        """Micro-batched frames (extra leading axis) go through one vmapped
        XLA call and match per-frame results."""
        be = TFLiteBackend()
        be.open(tflite_model, {})
        try:
            xs = np.stack([np.full((1, 4), float(i), np.float32)
                           for i in range(5)])          # (5, 1, 4)
            (out,) = be.invoke_batch([xs])
            out = np.asarray(out)
            assert out.shape == (5, 1, 4)
            np.testing.assert_allclose(out, xs * 2 + 1)
        finally:
            be.close()

    def test_reload_double_buffered(self, tflite_model, tmp_path):
        """reload() swaps to a different .tflite without reopening."""
        import flatbuffers
        from test_tflite_import import (
            _buffer, _ivec, _model, _opcode, _operator, _subgraph,
            _tensor, _F32, _MUL)

        b = flatbuffers.Builder(1024)
        bufs = [_buffer(b, b""),
                _buffer(b, np.full(4, 5.0, np.float32).tobytes())]
        tens = [_tensor(b, (1, 4), _F32, 0, "x"),
                _tensor(b, (1, 4), _F32, 1, "w"),
                _tensor(b, (1, 4), _F32, 0, "y")]
        ops = [_operator(b, 0, [0, 1], [2])]
        sg = _subgraph(b, tens, [0], [2], ops)
        b.Finish(_model(b, [_opcode(b, _MUL)], [sg], bufs),
                 file_identifier=b"TFL3")
        other = tmp_path / "times5.tflite"
        other.write_bytes(bytes(b.Output()))

        be = TFLiteBackend()
        be.open(tflite_model, {})
        try:
            x = np.ones((1, 4), np.float32)
            np.testing.assert_allclose(np.asarray(be.invoke([x])[0]),
                                       np.full((1, 4), 3.0))
            be.reload(str(other))
            np.testing.assert_allclose(np.asarray(be.invoke([x])[0]),
                                       np.full((1, 4), 5.0))
        finally:
            be.close()


@needs_ref_models
class TestTFLiteRealModels:
    def test_mobilenet_quant_pipeline(self):
        """The reference's flagship quant model end-to-end in a pipeline:
        uint8 image in, uint8 scores out, image_labeling-compatible."""
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=tflite "
            f"model={MOBILENET_QUANT} ! tensor_sink name=out"
        )
        pipe.start()
        img = np.random.default_rng(0).integers(
            0, 256, (1, 224, 224, 3), np.uint8)
        pipe["src"].push(img)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=300)
        frames = pipe["out"].frames
        pipe.stop()
        out = np.asarray(frames[0].tensors[0])
        assert out.shape == (1, 1001) and out.dtype == np.uint8

    def test_singleshot_fake_quant_prop(self):
        with SingleShot("tflite", MOBILENET_QUANT,
                        custom="fake_quant:false") as m:
            img = np.random.default_rng(1).integers(
                0, 256, (1, 224, 224, 3), np.uint8)
            (out,) = m.invoke([img])
            assert np.asarray(out).shape == (1, 1001)

    def test_deeplab_pipeline_with_segment_decoder(self):
        """The reference's deeplabv3 .tflite end-to-end: importer backend
        + tensor_decoder mode=image_segment (tflite-deeplab layout), the
        canonical reference segmentation pipeline."""
        model = os.path.join(MODELS, "deeplabv3_257_mv_gpu.tflite")
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=auto model={model} ! "
            "tensor_decoder mode=image_segment option1=tflite-deeplab ! "
            "tensor_sink name=out"
        )
        pipe.start()
        x = np.random.default_rng(6).random(
            (1, 257, 257, 3), np.float32) * 2 - 1
        pipe["src"].push(x)
        pipe["src"].end_of_stream()
        pipe.wait(timeout=300)
        frames = pipe["out"].frames
        pipe.stop()
        out = np.asarray(frames[0].tensors[0])
        # the decoder emits a palette-rendered RGBA overlay of the argmax
        # class grid plus a classes_present meta summary
        assert out.shape == (257, 257, 4) and out.dtype == np.uint8
        assert "classes_present" in frames[0].meta
