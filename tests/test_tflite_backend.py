"""tflite backend: real .tflite models through the interpreter runtime.

≙ reference ``tests/nnstreamer_filter_tensorflow2_lite/runTest.sh`` —
skips gracefully when no TFLite runtime is present (SURVEY §4 practice),
runs a real converted model otherwise.
"""

import numpy as np
import pytest

from nnstreamer_tpu.backends.tflite_import import TFLiteImportBackend
from nnstreamer_tpu.elements.filter import SingleShot, detect_framework
from nnstreamer_tpu.pipeline import parse_pipeline

pytestmark = pytest.mark.skipif(
    not TFLiteImportBackend.available(), reason="no TFLite runtime in image"
)


@pytest.fixture(scope="module")
def tflite_model(tmp_path_factory):
    """A tiny y = 2x + 1 model converted to .tflite."""
    import tensorflow as tf

    class M(tf.Module):
        @tf.function(input_signature=[tf.TensorSpec((1, 4), tf.float32)])
        def f(self, x):
            return {"y": x * 2.0 + 1.0}

    m = M()
    conv = tf.lite.TFLiteConverter.from_concrete_functions(
        [m.f.get_concrete_function()], m
    )
    path = tmp_path_factory.mktemp("tfl") / "affine.tflite"
    path.write_bytes(conv.convert())
    return str(path)


class TestTFLiteBackend:
    def test_pipeline_explicit_framework(self, tflite_model):
        pipe = parse_pipeline(
            f"appsrc name=src ! tensor_filter framework=tflite "
            f"model={tflite_model} ! tensor_sink name=out"
        )
        pipe.start()
        pipe["src"].push(np.full((1, 4), 3.0, np.float32))
        pipe["src"].end_of_stream()
        pipe.wait(timeout=60)
        frames = pipe["out"].frames
        pipe.stop()
        np.testing.assert_allclose(
            np.asarray(frames[0].tensors[0]), np.full((1, 4), 7.0)
        )

    def test_framework_auto_detects_tflite(self, tflite_model):
        # no arch: custom prop -> jax-xla cannot load a raw .tflite, so
        # extension priority falls through to the tflite runtime
        assert detect_framework(tflite_model) == "tflite"

    def test_single_shot(self, tflite_model):
        with SingleShot("tflite", tflite_model) as m:
            (out,) = m.invoke([np.zeros((1, 4), np.float32)])
            np.testing.assert_allclose(np.asarray(out), np.ones((1, 4)))

    def test_model_info(self, tflite_model):
        be = TFLiteImportBackend()
        be.open(tflite_model, {})
        in_spec, out_spec = be.get_model_info()
        assert in_spec.tensors[0].shape == (1, 4)
        assert out_spec.tensors[0].shape == (1, 4)
        be.close()
