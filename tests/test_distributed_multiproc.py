"""Among-device offload across OS processes (SURVEY §4: the reference tests
multi-"node" as multiple processes on localhost — gstTestBackground server +
foreground client) + client-side retry/failover (SURVEY §5.3)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline

_SERVER_SCRIPT = """
import sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from nnstreamer_tpu.pipeline import parse_pipeline

pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 ! "
    "tensor_transform mode=arithmetic option=add:100 ! "
    "tensor_query_serversink"
)
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep(60)
"""


class TestMultiProcessQuery:
    def test_client_offloads_to_server_process(self, tmp_path):
        script = tmp_path / "server.py"
        script.write_text(_SERVER_SCRIPT.format(
            repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        ))
        env = {**os.environ, "JAX_PLATFORMS": "cpu", "NNS_TPU_NO_NATIVE": "1"}
        srv = subprocess.Popen(
            [sys.executable, str(script)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        try:
            line = srv.stdout.readline()
            assert line.startswith("PORT "), line
            port = int(line.split()[1])

            pipe = parse_pipeline(
                f"appsrc name=a ! tensor_query_client port={port} "
                "timeout=30 ! tensor_sink name=out"
            )
            pipe.start()
            for i in range(4):
                pipe["a"].push(np.int32([i]))
            pipe["a"].end_of_stream()
            pipe.wait(timeout=60)
            pipe.stop()
            vals = [int(f.tensors[0][0]) for f in pipe["out"].frames]
            assert vals == [100, 101, 102, 103]  # +100 done in the other process
        finally:
            srv.kill()
            srv.wait(timeout=10)


class TestClientFailover:
    def test_dead_server_fails_over_to_live_one(self):
        # server pipeline in-process (separate pipeline object)
        server = parse_pipeline(
            "tensor_query_serversrc name=src port=0 id=7 ! "
            "tensor_transform mode=arithmetic option=mul:2 ! "
            "tensor_query_serversink id=7"
        )
        server.start()
        port = server["src"].props["port"]

        # first target is a dead port: every request must fail over
        dead = 1  # port 1: nothing listens there
        client = parse_pipeline(
            f"appsrc name=a ! tensor_query_client hosts=127.0.0.1:{dead},"
            f"127.0.0.1:{port} retries=2 timeout=3 ! tensor_sink name=out"
        )
        client.start()
        for i in range(4):
            client["a"].push(np.int32([i]))
        client["a"].end_of_stream()
        client.wait(timeout=60)
        client.stop()
        server.stop()
        vals = sorted(int(f.tensors[0][0]) for f in client["out"].frames)
        assert vals == [0, 2, 4, 6]

    def test_no_retries_surfaces_error(self):
        client = parse_pipeline(
            "appsrc name=a ! tensor_query_client host=127.0.0.1 port=1 "
            "retries=0 timeout=2 ! tensor_sink name=out"
        )
        client.start()
        client["a"].push(np.int32([1]))
        client["a"].end_of_stream()
        with pytest.raises(Exception):
            client.wait(timeout=30)
        client.stop()
