"""Among-device offload across OS processes (SURVEY §4: the reference tests
multi-"node" as multiple processes on localhost — gstTestBackground server +
foreground client) + client-side retry/failover (SURVEY §5.3)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from nnstreamer_tpu.pipeline import parse_pipeline

_SERVER_TEMPLATE = """
import sys, time
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from nnstreamer_tpu.pipeline import parse_pipeline

pipe = parse_pipeline({pipeline!r})
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep({lifetime})
"""


def spawn_server(pipeline_text: str, lifetime: float = 240.0,
                 extra_env=None):
    """Background server-pipeline process (≙ the reference's
    gstTestBackground); returns (proc, port).  Caller kills in finally.
    ``lifetime`` must exceed the client's total wait budget or a slow but
    healthy run loses its server mid-test."""
    src = _SERVER_TEMPLATE.format(
        repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        pipeline=pipeline_text,
        lifetime=lifetime,
    )
    env = {**os.environ, "JAX_PLATFORMS": "cpu", **(extra_env or {})}
    proc = subprocess.Popen(
        [sys.executable, "-c", src],
        stdout=subprocess.PIPE, text=True, env=env,
    )
    try:
        line = proc.stdout.readline()
        assert line.startswith("PORT "), line
        return proc, int(line.split()[1])
    except BaseException:
        # a failed handshake must not orphan the server for its full
        # lifetime (callers' finally only covers the post-return window)
        proc.kill()
        proc.wait(timeout=10)
        raise


class TestMultiProcessQuery:
    def test_client_offloads_to_server_process(self):
        srv, port = spawn_server(
            "tensor_query_serversrc name=src port=0 ! "
            "tensor_transform mode=arithmetic option=add:100 ! "
            "tensor_query_serversink",
            extra_env={"NNS_TPU_NO_NATIVE": "1"},
        )
        try:
            pipe = parse_pipeline(
                f"appsrc name=a ! tensor_query_client port={port} "
                "timeout=30 ! tensor_sink name=out"
            )
            pipe.start()
            for i in range(4):
                pipe["a"].push(np.int32([i]))
            pipe["a"].end_of_stream()
            pipe.wait(timeout=60)
            pipe.stop()
            vals = [int(f.tensors[0][0]) for f in pipe["out"].frames]
            assert vals == [100, 101, 102, 103]  # +100 done in the other process
        finally:
            srv.kill()
            srv.wait(timeout=10)


class TestClientFailover:
    def test_dead_server_fails_over_to_live_one(self):
        # server pipeline in-process (separate pipeline object)
        server = parse_pipeline(
            "tensor_query_serversrc name=src port=0 id=7 ! "
            "tensor_transform mode=arithmetic option=mul:2 ! "
            "tensor_query_serversink id=7"
        )
        server.start()
        port = server["src"].props["port"]

        # first target is a dead port: every request must fail over
        dead = 1  # port 1: nothing listens there
        client = parse_pipeline(
            f"appsrc name=a ! tensor_query_client hosts=127.0.0.1:{dead},"
            f"127.0.0.1:{port} retries=2 timeout=3 ! tensor_sink name=out"
        )
        client.start()
        for i in range(4):
            client["a"].push(np.int32([i]))
        client["a"].end_of_stream()
        client.wait(timeout=60)
        client.stop()
        server.stop()
        vals = sorted(int(f.tensors[0][0]) for f in client["out"].frames)
        assert vals == [0, 2, 4, 6]

    def test_no_retries_surfaces_error(self):
        client = parse_pipeline(
            "appsrc name=a ! tensor_query_client host=127.0.0.1 port=1 "
            "retries=0 timeout=2 ! tensor_sink name=out"
        )
        client.start()
        client["a"].push(np.int32([1]))
        client["a"].end_of_stream()
        with pytest.raises(Exception):
            client.wait(timeout=30)
        client.stop()


class TestGenerationOffload:
    """LLM generation served across OS processes: the query client
    offloads prompts to a server pipeline running KV-cache generation
    (distributed serving = the reference's among-device story composed
    with the net-new generation path)."""

    def test_prompts_offloaded_and_completed(self):
        srv, port = spawn_server(
            "tensor_query_serversrc name=src port=0 ! "
            "tensor_filter framework=jax-xla model=zoo "
            "custom=arch:transformer,dtype:float32,vocab:61,d_model:32,"
            "heads:2,layers:2,d_ff:64,seq:32,seed:11,generate:4 ! "
            "tensor_query_serversink",
            lifetime=300,  # > client 180s wait + 90s per-request budget
        )
        try:
            client = parse_pipeline(
                f"appsrc name=a ! tensor_query_client port={port} "
                "timeout=90 ! tensor_sink name=out"
            )
            client.start()
            rng = np.random.default_rng(4)
            prompts = [
                rng.integers(0, 61, (6,)).astype(np.int32) for _ in range(3)
            ]
            for p in prompts:
                client["a"].push(p)
            client["a"].end_of_stream()
            client.wait(timeout=180)
            client.stop()
            outs = [np.asarray(f.tensors[0]) for f in client["out"].frames]
            assert len(outs) == 3
            for p, o in zip(prompts, outs):
                assert o.shape == (10,)  # 6 prompt + 4 generated
                np.testing.assert_array_equal(o[:6], p)
        finally:
            srv.kill()
            srv.wait(timeout=10)


def test_fanout_server_template_pins_core():
    """bench_fanout's server template: sched_setaffinity line executes
    (pin to the first ALLOWED cpu id — cpuset-restricted hosts may not
    include 0) and the server still boots and prints its port."""
    import subprocess
    import sys as _sys

    if not hasattr(os, "sched_getaffinity"):
        pytest.skip("no sched_getaffinity on this platform")
    sys_path = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    _sys.path.insert(0, os.path.join(sys_path, "tools"))
    try:
        import bench_fanout
    finally:
        _sys.path.pop(0)
    pin_to = min(os.sched_getaffinity(0))
    script = bench_fanout._SCRIPTS["echo"].format(
        root=sys_path, work_ms=1, ct="tcp", pin_core=pin_to)
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    p = subprocess.Popen([_sys.executable, "-c", script],
                         stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = p.stdout.readline()
        assert line.startswith("PORT "), line
        assert len(os.sched_getaffinity(p.pid)) == 1
    finally:
        p.kill()
        p.wait(timeout=10)
