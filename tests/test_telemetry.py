"""Fleet telemetry tests: metrics registry + Prometheus exposition,
wire-propagated trace spans (both transports), the flight recorder, and
the fused/unfused parity + schema-lint gates.

The module autouses ``module_leak_check`` (extended in conftest to count
open metrics-exposition servers), so every endpoint opened here must be
closed by ``Pipeline.stop()`` — the acceptance contract."""

import json
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core.resilience import FAULTS
from nnstreamer_tpu.core.telemetry import (
    METRICS,
    REGISTRY,
    SPAN_META,
    Counter,
    FlightRecorder,
    Gauge,
    Histogram,
    MetricsRegistry,
    live_server_count,
)
from nnstreamer_tpu.pipeline import parse_pipeline


@pytest.fixture(scope="module", autouse=True)
def _leaks(module_leak_check):
    """Exposition servers/threads must never outlive their pipeline."""
    yield


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    FAULTS.reset()


# ---------------------------------------------------------------------------
# Registry units
# ---------------------------------------------------------------------------
class TestRegistry:
    def test_instruments_and_render(self):
        reg = MetricsRegistry()
        c = reg.counter("nns.query.delivered", {"pipeline": "t"})
        c.inc()
        c.inc(2)
        assert c.value == 3
        g = reg.gauge("nns.feed.window_occupancy", {"pipeline": "t"})
        g.set(4)
        h = reg.histogram("nns.query.rtt_seconds", {"pipeline": "t"})
        h.observe(0.004)
        h.observe(0.2)
        assert h.count == 2 and abs(h.sum - 0.204) < 1e-9
        text = reg.render_prometheus()
        assert "# TYPE nns_query_delivered counter" in text
        assert 'nns_query_delivered{pipeline="t"} 3' in text
        assert 'nns_feed_window_occupancy{pipeline="t"} 4' in text
        assert "# TYPE nns_query_rtt_seconds histogram" in text
        assert 'nns_query_rtt_seconds_count{pipeline="t"} 2' in text
        # bucket lines are cumulative and carry le=
        assert re.search(
            r'nns_query_rtt_seconds_bucket\{le="\+Inf",pipeline="t"\} 2',
            text)

    def test_unknown_name_refused(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="catalog"):
            reg.counter("nns.made.up_name")
        # the documented escape hatch: auto-mapped health keys
        reg.gauge("nns.health.some_key").set(1)

    def test_same_name_same_instrument(self):
        reg = MetricsRegistry()
        a = reg.counter("nns.query.retried", {"element": "q"})
        b = reg.counter("nns.query.retried", {"element": "q"})
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("nns.query.retried", {"element": "q"})

    def test_remove_labeled(self):
        reg = MetricsRegistry()
        reg.counter("nns.query.delivered", {"pipeline": "p1", "element": "q"})
        reg.counter("nns.query.delivered", {"pipeline": "p2", "element": "q"})
        assert reg.remove_labeled(pipeline="p1") == 1
        names = {tuple(sorted(s.labels.items())) for s in reg.collect()}
        assert (("element", "q"), ("pipeline", "p2")) in names
        assert all(("pipeline", "p1") not in lb for lb in names)

    def test_default_name_pipelines_do_not_alias(self):
        """Both Pipeline() and parse_pipeline() default to
        name=\"pipeline\": two concurrent defaults must get DISTINCT
        registry labels, and one's stop() must not evict the other's
        instruments or merge its samples (regression: remove_labeled by
        bare name)."""
        a = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        b = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        a.start()
        b.start()
        try:
            assert a.telemetry_label != b.telemetry_label
            a["src"].push(np.float32([1.0]))
            a["src"].end_of_stream()
            a.wait(timeout=10)
            # a's snapshot sees only its own delivery, not b's series
            assert a.metrics_snapshot().get("nns.pipeline.delivered") == 1
            assert b.metrics_snapshot().get("nns.pipeline.delivered") == 0
        finally:
            a.stop()
            b.stop()
        # labels released: a fresh default pipeline gets the bare name
        c = parse_pipeline("appsrc name=src ! tensor_sink name=out")
        try:
            assert c.telemetry_label == "pipeline"
        finally:
            c.stop()

    def test_collector_failure_survives(self):
        reg = MetricsRegistry()

        def bad():
            raise RuntimeError("collector bug")

        reg.register_collector(bad)
        assert reg.collect() == []  # scrape survives, returns what it has
        reg.unregister_collector(bad)

    def test_catalog_kinds_are_sane(self):
        assert all(kind in ("counter", "gauge", "histogram")
                   for kind, _ in METRICS.values())
        # spot-check the names the issue pins
        assert "nns.filter.invoke_latency" in METRICS
        assert "nns.feed.window_occupancy" in METRICS
        assert "nns.query.inflight" in METRICS


# ---------------------------------------------------------------------------
# Pipeline snapshot + Prometheus endpoint under load
# ---------------------------------------------------------------------------
def _parse_prometheus(text: str) -> dict:
    """Minimal exposition-format parser: {metric{labels}: float}.
    Raises on any malformed line — the 'parseable' acceptance check."""
    out = {}
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? "
        r"([-+]?[0-9.eE+-]+|NaN|[+-]Inf)$")
    for line in text.strip().splitlines():
        if not line or line.startswith("#"):
            continue
        m = line_re.match(line)
        assert m, f"unparseable exposition line: {line!r}"
        out[m.group(1) + (m.group(2) or "")] = float(m.group(3))
    return out


class TestExposition:
    def test_metrics_endpoint_under_load_and_clean_shutdown(self):
        """Acceptance: /metrics serves parseable Prometheus text holding
        filter, feed, query, and lifecycle series while a query server
        is under load; Pipeline.stop() closes the endpoint (the module
        leak check additionally pins the thread + socket)."""
        sid = 9301
        server = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            "max-inflight=16 ! "
            "tensor_filter name=f framework=scaler custom=factor:2 "
            "max-batch=4 ! "
            f"tensor_query_serversink id={sid}",
            name="metsrv",
        )
        server.enable_tracing()
        mport = server.serve_metrics(0)
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client name=q port={port} "
            "max-in-flight=8 ! tensor_sink name=out",
            name="metcli",
        )
        client.start()
        servers_open = live_server_count()
        assert servers_open >= 1
        try:
            # load + scrape concurrently: push a stream, scrape mid-flight
            n = 60
            text_mid = None
            for i in range(n):
                client["src"].push(np.float32([i]))
                if i == n // 2:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{mport}/metrics",
                            timeout=5) as r:
                        assert r.headers["Content-Type"].startswith(
                            "text/plain")
                        text_mid = r.read().decode()
            client["src"].end_of_stream()
            client.wait(timeout=30)
            metrics = _parse_prometheus(text_mid)

            def series(prefix):
                return [k for k in metrics if k.startswith(prefix)]

            # filter, feed, query, lifecycle series all present
            assert series("nns_filter_invokes")
            assert series("nns_feed_window_occupancy")
            assert series("nns_query_inflight")
            assert series("nns_query_admitted")
            assert series("nns_lifecycle_state")
            assert series("nns_lifecycle_server_state")
            # tracer-fed per-element series (tracing enabled server-side)
            assert series("nns_element_frames")
            # and the snapshot view agrees with health()
            snap = server.metrics_snapshot()
            admitted = server.health()["ssrc"]["admitted"]
            assert snap.get("nns.query.admitted", element="ssrc") == admitted
            assert snap.get("nns.query.inflight", element="ssrc") is not None
            vals = [float(f.tensors[0][0]) for f in client["out"].frames]
            assert vals == [2.0 * i for i in range(n)]
        finally:
            client.stop()
            server.stop()
        # endpoint down: connection refused, server census back to baseline
        assert live_server_count() == servers_open - 1
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            urllib.request.urlopen(
                f"http://127.0.0.1:{mport}/metrics", timeout=2)

    def test_snapshot_basics_without_tracer(self):
        pipe = parse_pipeline(
            "appsrc name=src ! identity ! tensor_sink name=out",
            name="snapbasic",
        )
        pipe.start()
        try:
            for i in range(7):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            snap = pipe.metrics_snapshot()
            assert snap.get("nns.pipeline.delivered") == 7
            assert snap.get("nns.sink.rendered", element="out") == 7
            assert snap.get("nns.source.pending", element="src") == 0
            # no tracer: the nns.element dataplane series are absent, the
            # supervision series still exported
            assert snap.get("nns.element.frames", element="out") is None
            assert snap.get("nns.element.dead_letters", element="out") == 0
            flat = pipe.telemetry_summary()
            assert flat["nns.pipeline.delivered"] == 7
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# Wire-propagated trace spans (acceptance e2e, both transports)
# ---------------------------------------------------------------------------
class TestWireSpans:
    @pytest.mark.parametrize("ct,sid", [("tcp", 9311), ("grpc", 9312)])
    def test_roundtrip_span_decomposition(self, ct, sid):
        """Acceptance: one tensor_query round trip yields a trace whose
        client-queue + wire + server-queue + device segments sum to the
        measured end-to-end latency within tolerance, with the
        per-segment breakdown visible in client health() and the
        registry."""
        server = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={sid} port=0 "
            f"connect-type={ct} ! "
            "tensor_filter framework=scaler custom=factor:3 ! "
            f"tensor_query_serversink id={sid}",
            name=f"spansrv{ct}",
        )
        server.start()
        port = server["ssrc"].props["port"]
        client = parse_pipeline(
            f"appsrc name=src ! tensor_query_client name=q port={port} "
            f"connect-type={ct} ! tensor_sink name=out",
            name=f"spancli{ct}",
        )
        client.start()
        try:
            # warm the path (dials, jit-less here, but first-RPC costs)
            for i in range(4):
                client["src"].push(np.float32([i]))
            deadline = time.time() + 15
            while len(client["out"].frames) < 4 and time.time() < deadline:
                time.sleep(0.01)
            assert len(client["out"].frames) == 4
            # one measured lone round trip: wall e2e from push to sink
            t_push = time.perf_counter()
            client["src"].push(np.float32([41.0]))
            while len(client["out"].frames) < 5 and time.time() < deadline:
                time.sleep(0.0005)
            wall_e2e = time.perf_counter() - t_push
            ans = client["out"].frames[-1]
            assert float(ans.tensors[0][0]) == 123.0
            span = ans.meta[SPAN_META]
            segments = (
                span["client_queue"] + span["wire"] + span["server_queue"]
                + span["device_dispatch"] + span["device_compute"]
            )
            # additive by construction: segments sum EXACTLY to total
            assert segments == pytest.approx(span["total"], abs=1e-9)
            # and total matches the externally measured e2e within
            # tolerance (the wall measurement additionally includes the
            # appsrc->client and client->sink mailbox hops + our 0.5ms
            # poll, so it upper-bounds the span)
            assert span["total"] <= wall_e2e + 1e-4
            assert wall_e2e - span["total"] < 0.25
            assert span["trace_id"]
            assert span["remote"].endswith(f":{port}")
            # every segment is a real, finite duration
            for key in ("client_queue", "wire", "server_queue",
                        "device_dispatch", "device_compute"):
                assert 0.0 <= span[key] <= span["total"]
            # server actually decomposed (not the legacy wire==rtt path)
            assert span["device_compute"] > 0.0
            # breakdown visible in client health() ...
            remotes = client.health()["q"]["remotes"]
            agg = remotes[span["remote"]]
            assert agg["requests"] == 5
            for key in ("e2e_ms", "rtt_ms", "wire_ms", "server_ms",
                        "client_queue_ms"):
                assert agg[key] is not None and agg[key] >= 0.0
            # ... and in the registry, labeled by remote
            snap = client.metrics_snapshot()
            assert snap.get("nns.query.remote_requests",
                            remote=span["remote"]) == 5
            assert snap.get("nns.query.remote_e2e_ms",
                            remote=span["remote"]) == pytest.approx(
                                agg["e2e_ms"], rel=1e-6)
            # the client-observed rtt histogram recorded every exchange
            assert snap.sum("nns.query.rtt_seconds_count", element="q") == 5
        finally:
            client.stop()
            server.stop()

    def test_trace_local_stamps_never_cross_the_wire(self):
        """The _nns_tl_ prefix (and the tracer's source stamp) are
        host-local: encode strips them; the trace id and the server
        duration dict DO cross."""
        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.core.telemetry import (
            SRV_SPAN_META,
            TL_ENQ_META,
            TL_RX_META,
            TRACE_ID_META,
        )
        from nnstreamer_tpu.core.tracer import META_SRC_TS
        from nnstreamer_tpu.distributed.wire import decode_frame, encode_frame

        f = TensorFrame([np.float32([1.0])], meta={
            TRACE_ID_META: "abc-1",
            TL_ENQ_META: 123.0,
            TL_RX_META: 124.0,
            META_SRC_TS: 125.0,
            SRV_SPAN_META: {"queue": 0.1, "dispatch": 0.0,
                            "compute": 0.2, "total": 0.3},
            "client_id": 7,
        })
        g = decode_frame(encode_frame(f))
        assert g.meta[TRACE_ID_META] == "abc-1"
        assert g.meta["client_id"] == 7
        assert g.meta[SRV_SPAN_META]["total"] == 0.3
        assert TL_ENQ_META not in g.meta
        assert TL_RX_META not in g.meta
        assert META_SRC_TS not in g.meta


# ---------------------------------------------------------------------------
# Flight recorder
# ---------------------------------------------------------------------------
class TestFlightRecorder:
    def test_stall_dump_contains_stuck_span(self, tmp_path):
        """Acceptance: an injected watchdog stall (FaultInjector hang
        site) produces a dump containing the stalled frame's span
        timeline — the hung element shows as an OPEN span with its
        trace id; the pipeline then restarts the element and loses
        nothing."""
        pipe = parse_pipeline(
            "appsrc name=src ! identity name=work stall-timeout=0.3 "
            "stall-policy=restart ! tensor_sink name=out",
            name="frstall",
        )
        pipe.enable_flight_recorder(dump_dir=str(tmp_path))
        # exactly ONE hang (times=1): the watchdog escalation interrupts
        # it cooperatively; the retry then runs clean
        FAULTS.arm("element.work.handle_frame", hang=True, after=2, times=1)
        pipe.start()
        try:
            for i in range(4):
                pipe["src"].push(np.float32([i]))
            deadline = time.time() + 15
            files = []
            while not files and time.time() < deadline:
                files = list(tmp_path.glob("nns_flight_*.json"))
                time.sleep(0.05)
            assert files, "no flight dump on watchdog stall"
            FAULTS.reset()  # release the hang -> StallError -> restart
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            data = json.loads(files[0].read_text())
            assert data["reason"].startswith("watchdog_")
            assert data["source"] == "work"
            stuck = [
                (t["trace_id"], s) for t in data["traces"]
                for s in t["spans"] if s.get("open")
            ]
            assert stuck, "dump lacks the stalled frame's open span"
            tid, s = stuck[0]
            assert s["element"] == "work"
            assert s["stuck_for_ms"] >= 300.0 - 50.0
            assert tid, "stalled frame has no trace id"
            # the stalled frame's earlier history is in the same dump:
            # frames 0/1 completed 'work' spans before the hang
            done = [
                sp for t in data["traces"] for sp in t["spans"]
                if not sp.get("open") and sp["element"] == "work"
            ]
            assert len(done) >= 2
            # zero loss: the restart retried the hung frame
            assert len(pipe["out"].frames) == 4
            assert pipe.health()["work"]["restarts"] == 1
            snap = pipe.metrics_snapshot()
            assert snap.get("nns.element.stalls", element="work") >= 1
        finally:
            FAULTS.reset()
            pipe.stop()

    def test_dead_letter_and_rate_limit(self, tmp_path):
        """Dead-letters dump too, and the recorder rate-limits: a burst
        of incidents produces ONE file inside the interval."""
        pipe = parse_pipeline(
            "appsrc name=src ! identity name=work error-policy=skip ! "
            "tensor_sink name=out",
            name="frskip",
        )
        pipe.enable_flight_recorder(
            dump_dir=str(tmp_path), min_dump_interval_s=60.0)
        FAULTS.arm("element.work.handle_frame",
                   exc=ValueError("poison"), every=2)
        pipe.start()
        try:
            for i in range(8):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            files = list(tmp_path.glob("nns_flight_*.json"))
            assert len(files) == 1  # 4 dead-letters, one dump (limited)
            rec = pipe.flight_recorder
            assert rec.dumps == 1 and rec.suppressed >= 3
            assert pipe.health()["work"]["dead_letters"] == 4
        finally:
            FAULTS.reset()
            pipe.stop()

    def test_recorder_units(self, tmp_path):
        class F:
            def __init__(self, tid):
                self.meta = {"_nns_trace_id": tid}

        rec = FlightRecorder(capacity=16, dump_dir=str(tmp_path),
                             min_dump_interval_s=0.0)
        rec.begin("a", F("t1"))
        rec.end("a", F("t1"), 1.0, 2.0, 1)
        rec.begin("b", F("t1"))  # never ends: open span
        tl = rec.timelines()
        assert [s["element"] for s in tl["t1"]] == ["a", "b"]
        assert tl["t1"][1]["open"] is True
        path = rec.dump("unit", "test")
        assert path and json.load(open(path))["traces"]


# ---------------------------------------------------------------------------
# Fused/unfused parity: per-element stats and registry counts identical
# ---------------------------------------------------------------------------
class TestFusedParity:
    N = 24

    def _run(self, fuse: bool):
        FAULTS.reset()
        pipe = parse_pipeline(
            "appsrc name=src ! identity name=a error-policy=skip ! "
            "identity name=b ! tensor_sink name=out",
            name="parity",  # SAME name both runs: labels must match too
            fuse=fuse,
        )
        tracer = pipe.enable_tracing()
        # deterministic poison: every 4th supervised call on 'a' fails
        FAULTS.arm("element.a.handle_frame",
                   exc=ValueError("poison"), every=4)
        pipe.start()
        try:
            for i in range(self.N):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=20)
            report = {
                name: {"frames": r["frames"], "calls": r["calls"]}
                for name, r in tracer.report().items()
            }
            counters = {
                key: v
                for key, v in pipe.metrics_snapshot().counters().items()
                # process-global pools accumulate across runs — excluded
                # (everything else is per-pipeline deterministic).  The
                # log2 latency histograms' bucket/sum series hold TIMING
                # (nondeterministic by nature), and queue-wait exists
                # only where mailboxes exist — which fusion elides by
                # design; their deterministic subset (handle-latency
                # _count) stays in and is additionally pinned by
                # test_handle_histogram_counts_identical.
                if not key[0].startswith("nns.pool.")
                and not key[0].endswith(("_bucket", "_sum"))
                and not key[0].startswith("nns.element.queue_wait_seconds")
            }
            health = {
                el: {k: entry[k] for k in (
                    "state", "dead_letters", "deadline_drops", "restarts")}
                for el, entry in pipe.health().items()
            }
            return report, counters, health
        finally:
            FAULTS.reset()
            pipe.stop()

    def test_stats_and_registry_counts_identical(self):
        """The supervision truth-table pipeline (skip policy + periodic
        poison) produces BYTE-IDENTICAL per-element tracer stats and
        registry counter values fused vs unfused."""
        rep_f, cnt_f, health_f = self._run(True)
        rep_u, cnt_u, health_u = self._run(False)
        assert rep_f == rep_u
        assert cnt_f == cnt_u
        assert health_f == health_u
        # and the truth table itself held: every 4th of 24 dead-letters
        assert health_f["a"]["dead_letters"] == 6
        assert dict(cnt_f)[
            ("nns.pipeline.delivered", (("pipeline", "parity"),))
        ] == self.N - 6

    def _run_hists(self, fuse: bool):
        """Handle-latency log2 histograms after the supervision
        truth-table pipeline: {element: (count, bucket_count_sum)}."""
        FAULTS.reset()
        pipe = parse_pipeline(
            "appsrc name=src ! identity name=a error-policy=skip ! "
            "identity name=b ! tensor_sink name=out",
            name="hparity", fuse=fuse,
        )
        tracer = pipe.enable_tracing()
        FAULTS.arm("element.a.handle_frame",
                   exc=ValueError("poison"), every=4)
        pipe.start()
        try:
            for i in range(self.N):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=20)
            out = {}
            for el, mname, h in tracer.latency_histograms():
                if mname != "nns.element.handle_seconds":
                    continue
                out[el] = (h.count, sum(h.state()))
            return out
        finally:
            FAULTS.reset()
            pipe.stop()

    def test_handle_histogram_counts_identical(self):
        """PR-11 satellite (PR-7 registry-parity discipline): on the
        supervision truth-table pipeline, each element's handle-latency
        histogram records BYTE-IDENTICAL observation counts fused vs
        unfused, and the per-bucket counts sum exactly to the total in
        both modes (no observation is lost or double-bucketed by the
        lock-free record path).  Bucket PLACEMENT is timing and is
        deliberately not compared."""
        hf = self._run_hists(True)
        hu = self._run_hists(False)
        assert set(hf) == set(hu) == {"a", "b", "out"}
        assert hf == hu
        for el, (count, bucket_sum) in hf.items():
            assert count == bucket_sum, (
                f"{el}: bucket counts do not sum to the total")
        # the truth table's exact shape: 'a' is called once per frame,
        # poison included (the handler raised INSIDE the call — it still
        # began and ended); b/out see only the 18 survivors
        assert hf["a"][0] == self.N
        assert hf["b"][0] == self.N - 6
        assert hf["out"][0] == self.N - 6


# ---------------------------------------------------------------------------
# Profilers: jax trace-session refcount hygiene + the incident-time
# thread sampler
# ---------------------------------------------------------------------------
class _FakeJaxProfiler:
    """Scripted stand-in for the jax.profiler singleton."""

    def __init__(self, fail_starts=0):
        self.fail_starts = fail_starts
        self.starts = []
        self.stops = 0

    def start_trace(self, d):
        if self.fail_starts > 0:
            self.fail_starts -= 1
            raise RuntimeError("injected start_trace failure")
        self.starts.append(d)

    def stop_trace(self):
        self.stops += 1


@pytest.fixture
def _clean_profiler():
    """Snapshot/restore the profiler module's global session state."""
    from nnstreamer_tpu.core import profiler

    refs, d = profiler._refs, profiler._dir
    yield profiler
    profiler._refs, profiler._dir = refs, d


class TestJaxTraceSession:
    def test_failed_start_leaves_state_fully_reset(self, monkeypatch,
                                                   _clean_profiler):
        """Satellite bugfix pin: a trace_start whose start_trace raises
        returns False with refs==0 and dir==None AND resets the jax
        singleton (stop_trace called best-effort) — so a later
        successful start from ANOTHER element enters the clean refs==0
        path instead of refcounting on top of stale state."""
        import jax

        profiler = _clean_profiler
        profiler._refs, profiler._dir = 0, None
        fake = _FakeJaxProfiler(fail_starts=1)
        monkeypatch.setattr(jax, "profiler", fake)
        assert profiler.trace_start("/tmp/t1") is False
        assert profiler._refs == 0 and profiler._dir is None
        assert fake.stops == 1  # the half-armed singleton was reset
        assert profiler.trace_active() is False
        # a subsequent start (different element, different dir) succeeds
        # through the clean refs==0 path
        assert profiler.trace_start("/tmp/t2") is True
        assert profiler._refs == 1 and profiler._dir == "/tmp/t2"
        assert fake.starts == ["/tmp/t2"]
        assert profiler.trace_active() is True
        # join + full teardown refcounts exactly
        assert profiler.trace_start("/tmp/t2") is True
        assert profiler._refs == 2
        profiler.trace_stop()
        assert profiler._refs == 1 and fake.stops == 1
        profiler.trace_stop()
        assert profiler._refs == 0 and profiler._dir is None
        assert fake.stops == 2

    def test_foreign_active_session_is_not_reset(self, monkeypatch,
                                                 _clean_profiler):
        """A start that fails because the jax singleton is ALREADY
        active (someone else's TensorBoard capture) must NOT be reset —
        the failure-path stop_trace would kill their trace mid-run."""
        import jax

        profiler = _clean_profiler
        profiler._refs, profiler._dir = 0, None

        class Busy(_FakeJaxProfiler):
            def start_trace(self, d):
                raise RuntimeError("profiler session already active")

        fake = Busy()
        monkeypatch.setattr(jax, "profiler", fake)
        assert profiler.trace_start("/tmp/t3") is False
        assert profiler._refs == 0 and profiler._dir is None
        assert fake.stops == 0  # the foreign session survives

    def test_profiler_active_gauge_via_health_collector(self, monkeypatch,
                                                        _clean_profiler):
        """Satellite pin: the filter's trace session surfaces as the
        `profiler_active` health key -> nns.profiler.active gauge via
        the ONE health-collector path (no duplicate series)."""
        import jax

        profiler = _clean_profiler
        profiler._refs, profiler._dir = 0, None
        monkeypatch.setattr(jax, "profiler", _FakeJaxProfiler())
        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=scaler "
            "custom=factor:2 trace=1 trace-dir=/tmp/nns_t ! "
            "tensor_sink name=out",
            name="profgauge",
        )
        pipe.start()
        try:
            assert pipe.health()["f"]["profiler_active"] == 1
            snap = pipe.metrics_snapshot()
            assert snap.get("nns.profiler.active", element="f") == 1.0
            samples = [
                s for s in snap.samples if s.name == "nns.profiler.active"
                and s.labels.get("element") == "f"
            ]
            assert len(samples) == 1  # one export path, one series
        finally:
            pipe.stop()
        assert profiler._refs == 0  # stop() released the session


class TestThreadProfiler:
    def test_samples_named_framework_thread(self):
        """A named framework thread parked in a known function shows up
        with that function in its collapsed top stack; ignored-prefix
        threads (Thread-N etc.) do not."""
        import threading
        import time as _time

        from nnstreamer_tpu.core.profiler import profile_threads

        release = threading.Event()

        def distinctive_parked_fn():
            release.wait(10)

        t = threading.Thread(target=distinctive_parked_fn,
                             name="tprof-seg", daemon=True)
        anon = threading.Thread(target=lambda: release.wait(10),
                                daemon=True)  # "Thread-N": ignored
        t.start()
        anon.start()
        try:
            prof = profile_threads(duration_s=0.15, hz=50)
        finally:
            release.set()
            t.join(timeout=5)
            anon.join(timeout=5)
        assert prof["samples"] >= 1
        assert "tprof-seg" in prof["threads"]
        top = prof["threads"]["tprof-seg"]["top_stacks"]
        assert top and top[0]["count"] >= 1
        assert "distinctive_parked_fn" in top[0]["stack"]
        assert not any(n.startswith("Thread-") for n in prof["threads"])

    def test_stall_dump_contains_stalled_threads_stack(self, tmp_path):
        """Acceptance: a watchdog-stall incident dump carries collapsed
        thread stacks NAMING the stalled element's streaming thread,
        with the hang site visible in its top stack — "where did the
        time go" from the dump file alone."""
        pipe = parse_pipeline(
            "appsrc name=src ! identity name=work stall-timeout=0.3 "
            "stall-policy=restart ! tensor_sink name=out",
            name="profstall", fuse=False,  # thread named after 'work'
        )
        pipe.enable_flight_recorder(dump_dir=str(tmp_path))
        FAULTS.arm("element.work.handle_frame", hang=True, after=2, times=1)
        pipe.start()
        try:
            for i in range(4):
                pipe["src"].push(np.float32([i]))
            deadline = time.time() + 15
            files = []
            while not files and time.time() < deadline:
                files = list(tmp_path.glob("nns_flight_*.json"))
                time.sleep(0.05)
            assert files, "no flight dump on watchdog stall"
            FAULTS.reset()  # release the hang -> restart, zero loss
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            data = json.loads(files[0].read_text())
            prof = data["thread_profile"]
            assert prof and prof["samples"] >= 1
            assert "work" in prof["threads"], sorted(prof["threads"])
            stacks = [
                s["stack"]
                for s in prof["threads"]["work"]["top_stacks"]
            ]
            # the hung thread is parked inside the injected fault's
            # cooperative hang (resilience.py) under the supervised
            # handler — its collapsed stack says so
            assert any("resilience.py" in s for s in stacks), stacks
            assert any("pipeline.py" in s for s in stacks), stacks
            assert len(pipe["out"].frames) == 4  # zero loss after restart
        finally:
            FAULTS.reset()
            pipe.stop()
        from nnstreamer_tpu.core.telemetry import REGISTRY

        caps = [
            s for s in REGISTRY.collect()
            if s.name == "nns.profiler.captures"
        ]
        assert caps and caps[0].value >= 1


# ---------------------------------------------------------------------------
# Always-on latency histograms (tentpole 2): snapshot + exposition
# ---------------------------------------------------------------------------
class TestLatencyHistograms:
    def test_log2_histogram_units(self):
        from nnstreamer_tpu.core.telemetry import (
            LOG2_NBUCKETS,
            Log2Histogram,
        )

        h = Log2Histogram()
        assert h.quantile(0.5) is None and h.percentiles_us() == {}
        for v in (2e-6, 2e-6, 2e-6, 1e-3, 1e-3, 0.25, 100.0):
            h.record(v)
        assert h.count == 7
        assert sum(h.state()) == 7
        assert h.sum == pytest.approx(100.252006, rel=1e-6)
        # overflow lands in the +Inf tail, never out of range
        assert h.state()[LOG2_NBUCKETS] == 1
        # quantile estimates respect bucket edges (log2 resolution)
        assert 1e-6 <= h.quantile(0.25) <= 4e-6
        assert 5e-4 <= h.quantile(0.65) <= 2e-3
        p = h.percentiles_us()
        assert p["p50"] <= p["p95"] <= p["p99"]
        # sub-resolution values land in bucket 0, not a crash
        h.record(1e-9)
        assert h.state()[0] >= 1

    def test_quantiles_in_summary_and_prometheus(self):
        """Acceptance: per-element p50/p95/p99 are visible in
        telemetry_summary() and on /metrics (via the registry's
        exposition render) with a tracer armed, window dwell included."""
        from nnstreamer_tpu.core.telemetry import REGISTRY

        pipe = parse_pipeline(
            "appsrc name=src ! tensor_filter name=f framework=async-sim "
            "custom=compute_ms:1 max-batch=4 dispatch-depth=4 ! "
            "tensor_sink name=out",
            name="histvis",
        )
        pipe.enable_tracing()
        pipe.start()
        try:
            for i in range(32):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=20)
            flat = pipe.telemetry_summary()
            for key in ("nns.element.handle_p50_us",
                        "nns.element.handle_p95_us",
                        "nns.element.handle_p99_us",
                        "nns.feed.window_dwell_p50_us",
                        "nns.feed.window_dwell_p99_us"):
                assert flat.get(key, 0) > 0, key
            # the compact summary never carries raw bucket series
            assert not any(k.endswith("_bucket") for k in flat)
            snap = pipe.metrics_snapshot()
            assert snap.get("nns.element.handle_p99_us",
                            element="f") > 0
            assert snap.sum("nns.feed.window_dwell_seconds_count",
                            element="f") >= 1
            text = REGISTRY.render_prometheus()
            assert "# TYPE nns_element_handle_seconds histogram" in text
            assert re.search(
                r'nns_element_handle_seconds_bucket\{[^}]*le="\+Inf"', text)
            assert "nns_feed_window_dwell_seconds_count" in text
            assert "nns_element_handle_p99_us" in text
            _parse_prometheus(text)  # parseable end to end
        finally:
            pipe.stop()

    def test_queue_wait_recorded_at_thread_boundaries(self):
        """Unfused (every element owns a mailbox): each consuming
        element records one queue-wait observation per frame; the
        stamps are host-local and never reach the wire."""
        from nnstreamer_tpu.core.telemetry import TL_QPUT_META

        pipe = parse_pipeline(
            "appsrc name=src ! identity name=a ! tensor_sink name=out",
            name="qwait", fuse=False,
        )
        pipe.enable_tracing()
        pipe.start()
        try:
            for i in range(10):
                pipe["src"].push(np.float32([i]))
            pipe["src"].end_of_stream()
            pipe.wait(timeout=15)
            snap = pipe.metrics_snapshot()
            for el in ("a", "out"):
                assert snap.sum("nns.element.queue_wait_seconds_count",
                                element=el) == 10, el
                assert snap.get("nns.element.queue_wait_p50_us",
                                element=el) >= 0
            # the dequeue popped the stamp off every delivered frame
            for f in pipe["out"].frames:
                assert TL_QPUT_META not in f.meta
        finally:
            pipe.stop()


# ---------------------------------------------------------------------------
# lint gate: health/metric schema stability (tier-1, like the other two)
# ---------------------------------------------------------------------------
def test_health_schema_lint_clean():
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent.parent / "tools"))
    try:
        import check_health_schema
    finally:
        sys.path.pop(0)
    bad = check_health_schema.scan()
    assert not bad, "health/metric schema problems:\n" + "\n".join(bad)


def test_train_health_keys_map_to_explicit_train_metrics():
    """The one-health-collector-path contract for continuous learning:
    every tensor_trainer / model_validator health key has an EXPLICIT
    ``nns.train.*`` mapping in HEALTH_KEY_METRICS backed by a registered
    metric — none may leak into the generic ``nns.health.*`` fallback
    namespace where dashboards would never find it."""
    from nnstreamer_tpu.core.telemetry import HEALTH_KEY_METRICS
    from nnstreamer_tpu.pipeline.element import make_element

    for factory, name in (("tensor_trainer", "train"),
                          ("model_validator", "gate")):
        el = make_element(factory, name)
        keys = el.health_info().keys()
        assert keys, f"{factory} reports no health keys"
        for key in keys:
            mname = HEALTH_KEY_METRICS.get(key)
            assert mname is not None, (
                f"{factory} health key {key!r} has no explicit metric "
                "mapping (would fall back to nns.health.*)")
            assert mname.startswith("nns.train."), (key, mname)
            assert mname in METRICS, f"{mname} not registered in METRICS"
